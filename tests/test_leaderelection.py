"""Operator leader election (VERDICT r1 coverage #4): lease protocol
against a fake apiserver with real conflict semantics, failover on
expiry, graceful release, and reconcile gating in the Operator."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest
import yaml

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.providers import ReplayProvider
from retina_tpu.crd.types import Capture
from retina_tpu.operator import CRDStore, Operator
from retina_tpu.operator.kubeclient import KubeClient
from retina_tpu.operator.leaderelection import LeaderElector

from test_capture_operator import make_source


class FakeLeaseApi(BaseHTTPRequestHandler):
    """coordination.k8s.io lease store with resourceVersion conflicts."""

    leases: dict = {}
    lock = threading.Lock()

    def log_message(self, *a):  # noqa: D102
        pass

    def _send(self, doc, code=200):
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        self.wfile.write(body)

    def _name(self):
        return self.path.split("?")[0].rstrip("/").split("/")[-1]

    def do_GET(self):  # noqa: N802
        with FakeLeaseApi.lock:
            lease = FakeLeaseApi.leases.get(self._name())
        if lease is None:
            self._send({"kind": "Status", "code": 404}, 404)
        else:
            self._send(lease)

    def do_POST(self):  # noqa: N802
        ln = int(self.headers.get("Content-Length", 0))
        doc = json.loads(self.rfile.read(ln))
        name = doc["metadata"]["name"]
        with FakeLeaseApi.lock:
            if name in FakeLeaseApi.leases:
                self._send({"kind": "Status", "code": 409}, 409)
                return
            doc["metadata"]["resourceVersion"] = "1"
            FakeLeaseApi.leases[name] = doc
        self._send(doc, 201)

    def do_PUT(self):  # noqa: N802
        ln = int(self.headers.get("Content-Length", 0))
        doc = json.loads(self.rfile.read(ln))
        name = self._name()
        with FakeLeaseApi.lock:
            cur = FakeLeaseApi.leases.get(name)
            if cur is None:
                self._send({"kind": "Status", "code": 404}, 404)
                return
            # Optimistic concurrency: stale writers lose with 409.
            if (doc.get("metadata", {}).get("resourceVersion")
                    != cur["metadata"]["resourceVersion"]):
                self._send({"kind": "Status", "code": 409}, 409)
                return
            doc["metadata"]["resourceVersion"] = str(
                int(cur["metadata"]["resourceVersion"]) + 1)
            FakeLeaseApi.leases[name] = doc
        self._send(doc)


@pytest.fixture()
def lease_apiserver(tmp_path):
    FakeLeaseApi.leases = {}
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), FakeLeaseApi)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    kc = tmp_path / "kc"
    kc.write_text(yaml.safe_dump({
        "clusters": [{"name": "c", "cluster": {
            "server": f"http://127.0.0.1:{httpd.server_address[1]}"}}],
        "contexts": [], "users": [],
    }))
    yield str(kc)
    httpd.shutdown()


def mk_elector(kubeconfig, ident, **kw):
    return LeaderElector(
        KubeClient(kubeconfig), identity=ident,
        lease_duration_s=kw.pop("lease_duration_s", 1.0),
        renew_period_s=kw.pop("renew_period_s", 0.2), **kw,
    )


def test_single_elector_acquires(lease_apiserver):
    a = mk_elector(lease_apiserver, "op-a")
    a.run_once()
    assert a.is_leader()
    lease = FakeLeaseApi.leases["retina-tpu-operator"]
    assert lease["spec"]["holderIdentity"] == "op-a"


def test_follower_does_not_lead_while_leader_renews(lease_apiserver):
    a = mk_elector(lease_apiserver, "op-a")
    b = mk_elector(lease_apiserver, "op-b")
    a.run_once()
    b.run_once()
    assert a.is_leader() and not b.is_leader()
    # Renewals keep the follower out.
    for _ in range(3):
        a.run_once()
        b.run_once()
        time.sleep(0.1)
    assert a.is_leader() and not b.is_leader()


def test_failover_on_expiry_and_graceful_release(lease_apiserver):
    a = mk_elector(lease_apiserver, "op-a")
    b = mk_elector(lease_apiserver, "op-b")
    a.run_once()
    assert a.is_leader()
    # Skew-safe expiry: b times the lease from its own FIRST observation
    # (never from the remote timestamp), so it must observe once, then
    # see a full duration pass with no renewal before seizing.
    b.run_once()
    assert not b.is_leader()
    time.sleep(1.2)  # a never renews
    b.run_once()
    assert b.is_leader()
    lease = FakeLeaseApi.leases["retina-tpu-operator"]
    assert lease["spec"]["holderIdentity"] == "op-b"
    assert lease["spec"]["leaseTransitions"] == 1
    # a comes back: it must observe b's live lease and follow.
    a.run_once()
    assert not a.is_leader()

    # Graceful release: stop() zeroes the holder; takeover is instant.
    b._leading = True
    b.stop()
    assert FakeLeaseApi.leases["retina-tpu-operator"]["spec"][
        "holderIdentity"] == ""
    a.run_once()
    assert a.is_leader()


def test_operator_follower_defers_until_leading(lease_apiserver):
    """A capture applied while following does not run; resync() on
    leadership runs it (controller-runtime gating analog)."""
    store = CRDStore()
    leading = {"v": False}
    op = Operator(
        store, node_name="local",
        capture_manager=CaptureManager(
            provider=ReplayProvider(source=make_source())),
        leading=lambda: leading["v"],
    )
    op.start()
    cap = Capture.from_yaml(yaml.safe_dump({
        "apiVersion": "retina.sh/v1alpha1",
        "kind": "Capture",
        "metadata": {"name": "gated", "namespace": "default"},
        "spec": {
            "captureTarget": {"nodeNames": ["local"]},
            "outputConfiguration": {"hostPath": "/tmp/le-art"},
            "duration": 1,
        },
    }))
    store.apply("Capture", cap)
    op.wait_capture("gated", timeout=2.0)
    assert cap.status.phase == "Pending"  # follower did nothing

    leading["v"] = True
    op.resync()
    op.wait_capture("gated", timeout=30.0)
    assert cap.status.phase == "Completed"


def test_resync_fails_orphaned_running_captures():
    """A capture left Running by a crashed leader has no job thread in
    THIS process; resync must fail it (its jobs died with the leader)
    instead of stranding it Running forever."""
    store = CRDStore()
    synced = []
    op = Operator(store, node_name="local",
                  status_sink=lambda kind, obj: synced.append(obj))
    op.start()
    cap = Capture.from_yaml(yaml.safe_dump({
        "apiVersion": "retina.sh/v1alpha1",
        "kind": "Capture",
        "metadata": {"name": "orphan", "namespace": "default"},
        "spec": {
            "captureTarget": {"nodeNames": ["elsewhere"]},
            "outputConfiguration": {"hostPath": "/tmp/x"},
            "duration": 1,
        },
        "status": {"phase": "Running", "jobs_active": 2},
    }))
    store.apply("Capture", cap)
    op.resync()
    assert cap.status.phase == "Failed"
    assert cap.status.jobs_active == 0
    assert cap.status.jobs_failed == 2
    assert "failover" in cap.status.message
    assert synced and synced[-1] is cap  # pushed to the backend

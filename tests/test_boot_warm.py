"""Boot-latency contract (VERDICT r4 #2): ``compile()`` warms only the
steady-state jit keys — the agent is ready in seconds, not after the
full bucket grid — and ``start_background_warm`` then makes EVERY
reachable bucket key resident so no live dispatch can hit a cold compile
once the warm finishes.

Reference SLA spirit: pkg/managers/pluginmanager/pluginmanager.go:25-28
(the whole plugin reconcile budget is 10s)."""

from __future__ import annotations

import threading

import numpy as np

from retina_tpu.config import Config
from retina_tpu.engine import SketchEngine
from retina_tpu.events.synthetic import TrafficGen


def small_cfg(**kw) -> Config:
    cfg = Config()
    cfg.batch_capacity = 1 << 10
    cfg.n_pods = 1 << 6
    cfg.cms_width = 1 << 10
    cfg.cms_depth = 2
    cfg.topk_slots = 1 << 6
    cfg.hll_precision = 8
    cfg.entropy_buckets = 1 << 8
    cfg.conntrack_slots = 1 << 10
    cfg.identity_slots = 1 << 8
    cfg.flow_dict_slots = 1 << 12
    cfg.transfer_min_bucket = 64
    cfg.bypass_lookup_ip_of_interest = True
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def test_compile_warms_only_steady_state_keys():
    """The boot critical path compiles the full-capacity step and the
    min plain bucket ONLY — the flow-dict pairs (including the min
    bucket), window-close and snapshot programs all belong to the
    background warm (the min dict pair + snapshot warms were ~30s of
    the 45s boot observed in the r5 dry run; the 96s boot of BENCH_r04
    was the whole grid)."""
    eng = SketchEngine(small_cfg(feed_coalesce_windows=4))
    eng.compile()
    keys = set(eng._pad_cache)
    grid = [k for k in keys if k[0] in ("new", "known")]
    assert not grid, f"flow-dict keys on the critical path: {grid}"
    # Bounded: plain capacity key + plain min key (+ nothing that
    # scales with the grid).
    assert len(keys) <= 3, sorted(keys, key=str)


def test_background_warm_covers_every_reachable_bucket():
    """After bucket_warm_done, any bucket the feed can produce — every
    _wire_bucket(n) for n in [0, coal_cap] — must already be compiled:
    no mid-feed cold compile at any reachable bucket. Live dispatches
    interleave with the warm (FIFO proxy queue)."""
    eng = SketchEngine(small_cfg(feed_coalesce_windows=2))
    eng.compile()
    t = eng.start_background_warm()
    # Feed while the warm runs: dispatches must interleave, not wedge.
    gen = TrafficGen(n_flows=200, n_pods=32, seed=7)
    for i in range(3):
        eng.step_records(gen.batch(512), now_s=10 + i)
    assert eng.bucket_warm_done.wait(300.0), "background warm never done"
    t.join(10.0)
    coal_cap = eng.cfg.batch_capacity * eng.cfg.feed_coalesce_windows
    probes = set(range(0, coal_cap + 1, 97)) | {0, 1, coal_cap}
    for n in probes:
        wb = eng._wire_bucket(n)
        assert ("new", wb) in eng._pad_cache, (n, wb)
        assert ("known", wb) in eng._pad_cache, (n, wb)
    snap = eng.snapshot(max_age_s=0)
    assert int(np.asarray(snap["totals"]).sum()) > 0


def test_background_warm_plain_mode_covers_coalesced_buckets():
    cfg = small_cfg(feed_coalesce_windows=3)
    cfg.wire_flow_dict = False
    eng = SketchEngine(cfg)
    assert eng._flow_dict is None
    eng.compile()
    eng.start_background_warm()
    assert eng.bucket_warm_done.wait(300.0)
    packed = bool(cfg.transfer_packed)
    for b in eng._reachable_buckets():
        assert (b, packed) in eng._pad_cache, b


def test_warm_close_is_first_background_job():
    """The window-close program heads the background-warm job list —
    ahead of even the min-bucket dispatch pair. The first live window
    tick fires window_seconds after boot, almost always before any
    grid key compiles; with warm_close queued first the tick finds the
    program resident (or deferring, below) instead of cold-compiling
    end_window inline on the proxy mid-feed."""
    eng = SketchEngine(small_cfg(feed_coalesce_windows=2))
    jobs = eng._warm_jobs()
    assert jobs[0][0] == "window close", [k for k, _, _ in jobs[:3]]
    # And the plain-wire grid keeps the same head.
    cfg = small_cfg(feed_coalesce_windows=2)
    cfg.wire_flow_dict = False
    assert SketchEngine(cfg)._warm_jobs()[0][0] == "window close"


def test_pre_warm_window_tick_defers_instead_of_inline_compile():
    """A window tick arriving while the close program is still queued in
    the background warm DEFERS (windows_deferred) instead of compiling
    end_window inline; once the program is resident the next tick
    closes normally."""
    from retina_tpu.events.synthetic import TrafficGen
    from retina_tpu.metrics import get_metrics

    eng = SketchEngine(small_cfg(feed_coalesce_windows=2))
    eng.compile()
    gen = TrafficGen(n_flows=100, n_pods=16, seed=11)
    eng.step_records(gen.batch(256), now_s=10)

    class _StuckWarm:
        """A warm thread that never finishes (compiles wedged)."""

        def is_alive(self) -> bool:
            return True

    eng._warm_thread = _StuckWarm()
    m = get_metrics()
    closed0 = m.windows_closed._value.get()
    eng._close_window()
    assert m.windows_deferred._value.get() == 1
    assert m.windows_closed._value.get() == closed0
    # Close program lands (warm's first job sets the event) -> the next
    # tick must close the (longer) window with every event intact.
    eng._close_warmed.set()
    eng._close_window()
    eng._harvest_window()
    assert m.windows_deferred._value.get() == 1
    assert m.windows_closed._value.get() == closed0 + 1


def test_background_warm_stops_early_on_shutdown():
    eng = SketchEngine(small_cfg(feed_coalesce_windows=2))
    eng.compile()
    stop = threading.Event()
    stop.set()  # shutdown before the warm starts walking the grid
    t = eng.start_background_warm(stop)
    t.join(30.0)
    assert not t.is_alive()
    # Done is NOT set on an aborted warm — nobody may conclude the grid
    # is resident.
    assert not eng.bucket_warm_done.is_set()


def test_desc_table_warm_job_in_flow_dict_mode():
    """Flow-dict dispatch needs the device descriptor table on its
    very first batch; the background warm builds it right behind the
    window-close program so the zeros-jit compile (and, post-resync,
    the AOT disk-cache load) stays off the event path (RT401)."""
    eng = SketchEngine(small_cfg(feed_coalesce_windows=2))
    jobs = [k for k, _, _ in eng._warm_jobs()]
    assert jobs[0] == "window close"
    assert jobs[1] == "desc table", jobs[:3]
    # Plain-wire mode has no flow dict and no desc table to warm.
    cfg = small_cfg(feed_coalesce_windows=2)
    cfg.wire_flow_dict = False
    plain = [k for k, _, _ in SketchEngine(cfg)._warm_jobs()]
    assert "desc table" not in plain


def test_wait_bucket_warm_polls_both_terminal_events():
    """bench.run_e2e's warm wait must react to bucket_warm_failed
    immediately — a failed warm never sets bucket_warm_done, and
    waiting on done alone burned the full 600s cap before measuring
    (ISSUE 20 satellite; WaitWarm's contract)."""
    import bench

    class StubEngine:
        def __init__(self):
            self.bucket_warm_done = threading.Event()
            self.bucket_warm_failed = threading.Event()

    logs: list[str] = []
    failed = StubEngine()
    failed.bucket_warm_failed.set()
    dt, incomplete = bench.wait_bucket_warm(
        failed, 600, emit=logs.append, sleep_s=0.01)
    assert dt is None and not incomplete
    assert any("FAILED" in line for line in logs)

    done = StubEngine()
    done.bucket_warm_done.set()
    dt, incomplete = bench.wait_bucket_warm(
        done, 600, emit=logs.append, sleep_s=0.01)
    assert dt is not None and dt < 5.0 and not incomplete

    stuck = StubEngine()
    dt, incomplete = bench.wait_bucket_warm(
        stuck, 0.05, emit=logs.append, sleep_s=0.01)
    assert incomplete and dt is not None and dt >= 0.05

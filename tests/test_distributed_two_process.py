"""Two-process jax.distributed mesh: the DCN-collectives claim, executed.

VERDICT r3 weak #5: the multi-host path (daemon.py run_agent ->
jax.distributed.initialize) rested on zero executed code. This test
spawns TWO real OS processes with a coordinator; each owns 2 virtual CPU
devices and they form one 4-device global mesh. The sharded step runs as
a multi-controller SPMD program and the snapshot's psum merge crosses
the process boundary (the DCN analog — same collectives, same program,
gRPC instead of ICI).

Opt-in (RETINA_DISTRIBUTED_TESTS=1): each child is a full JAX process
(~20s startup on CPU); CI runs it as a dedicated job
(.github/workflows/distributed.yaml) so the default suite stays fast.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("RETINA_DISTRIBUTED_TESTS") != "1",
    reason="opt-in: set RETINA_DISTRIBUTED_TESTS=1 (spawns 2 JAX procs)",
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_mesh_step_and_snapshot_merge():
    port = _free_port()
    child = os.path.join(os.path.dirname(__file__), "_dist_child.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # child sets its own device count
    procs = [
        subprocess.Popen(
            [sys.executable, child, str(pid), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(child))),
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, (
            f"process {pid} failed (rc={p.returncode}):\n{out[-4000:]}"
        )
        assert f"DIST_OK pid={pid} events=2048" in out, out[-2000:]

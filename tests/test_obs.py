"""Flight-recorder observability tier (retina_tpu/obs/).

Covers the PR-13 acceptance gates: the recorder's bounded-overhead
contract (<3% on a host-path probe), RFLT codec compatibility in both
directions around the optional trace-context header field, the debug
endpoints (/debug/trace Chrome JSON, /debug/profile single-flight +
cooldown + SHEDDING refusal), and the AOT disk-cache regression fix
(a second warm from the same cache dir deserializes everything —
misses == 0).
"""

import dataclasses
import json
import os
import time
import urllib.error
import urllib.request

import msgpack
import numpy as np
import pytest

from retina_tpu.config import Config
from retina_tpu.fleet.codec import (
    FleetSnapshot, decode_snapshot, encode_snapshot,
)
from retina_tpu.obs.debug import DebugObservability, thread_stacks
from retina_tpu.obs.recorder import (
    FlightRecorder, get_recorder, initialize_recorder,
)
from retina_tpu.runtime.overload import SHEDDING
from retina_tpu.server import Server
from retina_tpu.utils import metric_names as mn


# ------------------------------------------------------------ recorder

class TestFlightRecorder:
    def test_begin_record_span(self):
        rec = FlightRecorder(capacity=64)
        t0 = rec.begin()
        assert t0 > 0.0
        rec.record(mn.STAGE_HARVEST, t0, trace_id=7)
        (span,) = rec.spans()
        assert span["stage"] == mn.STAGE_HARVEST
        assert span["trace_id"] == 7
        assert span["t1"] >= span["t0"] == t0

    def test_sampling_gate(self):
        rec = FlightRecorder(capacity=64, sample_every=4)
        kept = 0
        for _ in range(20):
            t0 = rec.begin()
            rec.record(mn.STAGE_PUBLISH, t0)
            kept += bool(t0)
        assert kept == 5
        assert len(rec.spans()) == 5

    def test_disabled_recorder_records_nothing(self):
        rec = FlightRecorder(capacity=64, enabled=False)
        assert rec.begin() == 0.0
        rec.record(mn.STAGE_PUBLISH, time.perf_counter())
        assert rec.spans() == []

    def test_explicit_t1_bypasses_gate(self):
        # Sites that already hold both timestamps (transfer/step) pass
        # t1 explicitly; sampling never drops them.
        rec = FlightRecorder(capacity=64, sample_every=1000)
        rec.record(mn.STAGE_TRANSFER, 1.0, trace_id=3, t1=2.0)
        (span,) = rec.spans()
        assert span["t1"] - span["t0"] == 1.0

    def test_torn_slot_tolerated(self):
        rec = FlightRecorder(capacity=16)
        rec.record(mn.STAGE_HARVEST, 1.0, t1=2.0)
        ring = rec._ring()
        # Simulate a torn (half-written) slot: t1 behind t0.
        ring.slots[5][0] = mn.STAGE_PUBLISH
        ring.slots[5][1] = 9.0
        ring.slots[5][2] = 1.0
        assert [s["stage"] for s in rec.spans()] == [mn.STAGE_HARVEST]

    def test_ring_wraps_bounded(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.record(mn.STAGE_PUBLISH, float(i), t1=float(i) + 0.5)
        spans = rec.spans()
        assert len(spans) == 16
        assert spans[-1]["t0"] == 99.0

    def test_ring_wrap_thousands_reads_well_formed(self):
        """Tier-1 wrap gate: thousands of REAL wraps on a tiny ring,
        then every read surface (spans/chrome_trace/stage_report) must
        stay well-formed — including with a torn slot present and with
        the count counter pushed past 1M (Python ints: the counter
        must never truncate or go negative, there is no 32-bit wrap)."""
        rec = FlightRecorder(capacity=16)
        rec._metrics_broken = True  # skip exposition: pure ring path
        n = 64_000  # 4000 full wraps of the 16-slot ring
        for i in range(n):
            # t0 strictly > 0.0 (0.0 is the sampled-out sentinel)
            rec.record(mn.STAGE_PUBLISH, i + 1.0, trace_id=i,
                       t1=i + 1.5)
        ring = rec._ring()
        assert ring.count == n  # exact, monotonic
        assert ring.pos == n % 16
        # Torn slot mid-ring: reader must skip it, writer never cares.
        ring.slots[3][0] = mn.STAGE_HARVEST
        ring.slots[3][1] = 9e9
        ring.slots[3][2] = 1.0
        spans = rec.spans()
        assert len(spans) == 15  # capacity minus the torn slot
        assert all(s["t1"] >= s["t0"] for s in spans)
        assert spans[-1]["trace_id"] == n - 1  # newest retained
        # Fabricate a multi-million historical count (a long soak's
        # magnitude): diagnostics must report it exactly.
        ring.count = 3_141_592_653
        assert rec.stats()["threads"][ring.name] == 3_141_592_653
        doc = rec.chrome_trace()
        assert len(json.loads(json.dumps(doc))["traceEvents"]) >= 15
        rep = rec.stage_report()
        assert rep[mn.STAGE_PUBLISH]["count"] == 15

    @pytest.mark.slow
    def test_ring_wrap_past_one_million_real(self):
        """>1M REAL spans through one 16-slot ring (the soak's order of
        magnitude, no fabricated counters): count stays exact, reads
        stay bounded and well-formed, the trace dump stays valid JSON."""
        rec = FlightRecorder(capacity=16)
        rec._metrics_broken = True
        n = 1_200_000
        for i in range(n):
            rec.record(mn.STAGE_PUBLISH, i + 1.0, trace_id=i,
                       t1=i + 1.5)
        ring = rec._ring()
        assert ring.count == n
        assert ring.pos == n % 16
        spans = rec.spans()
        assert len(spans) == 16  # bounded by capacity, not history
        assert [s["trace_id"] for s in spans] == list(
            range(n - 16, n)
        )
        assert all(s["t1"] > s["t0"] for s in spans)
        doc = json.loads(json.dumps(rec.chrome_trace()))
        assert len([e for e in doc["traceEvents"]
                    if e["ph"] == "X"]) == 16
        assert rec.stage_report()[mn.STAGE_PUBLISH]["count"] == 16

    def test_stage_report_percentiles(self):
        rec = FlightRecorder(capacity=256)
        for i in range(100):
            rec.record(mn.STAGE_DEVICE_STEP, 1.0,
                       t1=1.0 + (i + 1) / 1000)
        rep = rec.stage_report()
        stats = rep[mn.STAGE_DEVICE_STEP]
        assert stats["count"] == 100
        assert stats["p50_s"] == pytest.approx(0.051)
        assert stats["p99_s"] == pytest.approx(0.100)

    def test_stage_report_pipeline_order(self):
        rec = FlightRecorder(capacity=64)
        rec.record(mn.STAGE_PUBLISH, 1.0, t1=2.0)
        rec.record(mn.STAGE_GENERATOR_EMIT, 1.0, t1=2.0)
        assert list(rec.stage_report()) == [
            mn.STAGE_GENERATOR_EMIT, mn.STAGE_PUBLISH,
        ]

    def test_chrome_trace_shape(self):
        rec = FlightRecorder(capacity=64)
        rec.record(mn.STAGE_HARVEST, 1.0, trace_id=42, t1=1.5)
        doc = rec.chrome_trace()
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        metas = [e for e in events if e["ph"] == "M"]
        xs = [e for e in events if e["ph"] == "X"]
        assert len(metas) == 1 and len(xs) == 1
        assert xs[0]["name"] == mn.STAGE_HARVEST
        assert xs[0]["dur"] == pytest.approx(0.5e6)
        assert xs[0]["args"]["trace_id"] == 42

    def test_observes_stage_histogram(self):
        from retina_tpu.metrics import get_metrics

        rec = FlightRecorder(capacity=64)
        rec.record(mn.STAGE_WINDOW_CLOSE, 1.0, t1=1.25)
        child = get_metrics().stage_seconds.labels(
            stage=mn.STAGE_WINDOW_CLOSE
        )
        assert child._sum.get() == pytest.approx(0.25)

    def test_initialize_replaces_singleton(self):
        old = get_recorder()
        try:
            rec = initialize_recorder(capacity=32, sample_every=2,
                                      enabled=True)
            assert get_recorder() is rec
            assert rec.capacity == 32 and rec.sample_every == 2
        finally:
            initialize_recorder(capacity=old.capacity,
                                sample_every=old.sample_every,
                                enabled=old.enabled)

    @pytest.mark.load
    def test_overhead_under_three_percent(self):
        """The acceptance gate: recorder on vs off on a host-path
        probe shaped like a feed-worker flush (a chunky numpy quantum
        bracketed by one begin/record pair).

        The 1.03 gate is the contract and stays; min-of-5 absorbs
        per-iteration noise but a busy box can still skew one whole
        measurement block (a concurrent bench run stealing the core
        mid-block flaked this in the PR-17 suite run). Two defenses,
        both measurement-side: on/off samples INTERLEAVE so a noise
        burst lands on both sides of the ratio instead of inflating
        only the numerator (sequential blocks flaked twice in the
        PR-19 suite runs), and the block is retried up to 6 times with
        the BEST ratio judged — scheduler interference can only
        inflate the ratio, never deflate it, so taking the quietest
        attempt measures the recorder, not the neighbors."""
        a = np.random.default_rng(0).random((256, 256))

        def probe(rec, iters=200):
            t = time.perf_counter()
            for _ in range(iters):
                t0 = rec.begin()
                (a @ a).sum()
                rec.record(mn.STAGE_FEED_FILL, t0, trace_id=1)
            return time.perf_counter() - t

        on = FlightRecorder(capacity=1024, enabled=True)
        off = FlightRecorder(capacity=1024, enabled=False)
        probe(on, 20)
        probe(off, 20)  # warm caches / histogram child
        best = float("inf")
        for _attempt in range(6):
            t_on, t_off = float("inf"), float("inf")
            for _ in range(5):
                t_on = min(t_on, probe(on))
                t_off = min(t_off, probe(off))
            best = min(best, t_on / t_off)
            if best < 1.03:
                break
        assert best < 1.03, best


# ------------------------------------------- RFLT codec trace context

def _snap(trace=None):
    return FleetSnapshot(
        node="n0", tenant="t0", priority=1, epoch=17, seq=3,
        window_s=15.0, seeds={"flow": 1},
        arrays={
            "flow_cms": np.arange(8, dtype=np.uint32).reshape(2, 4),
            "totals": np.arange(8, dtype=np.uint32),
        },
        trace=trace,
    )


class TestCodecTraceContext:
    def test_round_trip_with_trace(self):
        snap = _snap(trace={"tid": 17, "node": "n0"})
        out = decode_snapshot(encode_snapshot(snap))
        assert out.trace == {"tid": 17, "node": "n0"}
        assert out.epoch == 17
        np.testing.assert_array_equal(
            out.arrays["flow_cms"], snap.arrays["flow_cms"]
        )

    def test_traceless_frame_byte_identical_to_legacy(self):
        """trace=None is omitted from the wire entirely, so encoders
        without the field produce the exact same bytes (old and new
        agents interop byte-for-byte)."""
        frame = encode_snapshot(_snap(trace=None))
        (hlen,) = np.frombuffer(frame[5:9], np.uint32)
        hdr = msgpack.unpackb(frame[9:9 + int(hlen)], raw=False)
        assert "trace" not in hdr
        out = decode_snapshot(frame)
        assert out.trace is None
        # Adding then removing the field reproduces the legacy bytes.
        assert frame == encode_snapshot(
            dataclasses.replace(_snap(trace={"tid": 1}), trace=None)
        )

    def test_old_decoder_shape_tolerates_unknown_header_keys(self):
        """Forward compatibility: the decoder ignores header keys it
        does not know — the same property that lets a pre-trace
        decoder accept frames from a trace-stamping shipper."""
        frame = encode_snapshot(_snap(trace={"tid": 17}))
        (hlen,) = np.frombuffer(frame[5:9], np.uint32)
        hdr = msgpack.unpackb(frame[9:9 + int(hlen)], raw=False)
        hdr["future_field"] = {"x": 1}
        new_hdr = msgpack.packb(hdr, use_bin_type=True)
        rebuilt = (
            frame[:5]
            + np.uint32(len(new_hdr)).tobytes()
            + new_hdr
            + frame[9 + int(hlen):]
        )
        out = decode_snapshot(rebuilt)
        assert out.trace == {"tid": 17}
        assert out.node == "n0"

    def test_malformed_trace_field_degrades_to_none(self):
        frame = encode_snapshot(_snap(trace=None))
        (hlen,) = np.frombuffer(frame[5:9], np.uint32)
        hdr = msgpack.unpackb(frame[9:9 + int(hlen)], raw=False)
        hdr["trace"] = "not-a-dict"
        new_hdr = msgpack.packb(hdr, use_bin_type=True)
        rebuilt = (
            frame[:5]
            + np.uint32(len(new_hdr)).tobytes()
            + new_hdr
            + frame[9 + int(hlen):]
        )
        assert decode_snapshot(rebuilt).trace is None


# ------------------------------------------------- debug HTTP surface

def _request(port, path, method="GET", timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=b"" if method == "POST" else None,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


class _Overload:
    def __init__(self, state):
        self.state = state


@pytest.fixture
def debug_srv(tmp_path):
    servers = []

    def make(overload=None, **cfg_kw):
        cfg = Config(
            profile_artifact_dir=str(tmp_path / "prof"),
            profile_max_seconds=0.5,
            profile_cooldown_s=0.2,
            **cfg_kw,
        )
        srv = Server("127.0.0.1:0")
        srv.start()
        servers.append(srv)
        dbg = DebugObservability(cfg, overload=overload)
        dbg.attach(srv)
        return srv, dbg

    yield make
    for s in servers:
        s.stop()


class TestDebugEndpoints:
    def test_trace_endpoint_serves_chrome_json(self, debug_srv):
        srv, dbg = debug_srv()
        dbg.recorder.record(mn.STAGE_HARVEST, 1.0, trace_id=5, t1=1.5)
        code, body = _request(srv.port, "/debug/trace?last=10")
        assert code == 200
        doc = json.loads(body)
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "X"}
        assert mn.STAGE_HARVEST in names

    def test_trace_endpoint_valid_json_after_ring_wrap(self, debug_srv):
        """/debug/trace must serve valid Chrome JSON after the ring has
        wrapped thousands of times (bounded body, newest spans only) —
        the soak hits this endpoint with span counts in the millions."""
        srv, dbg = debug_srv()
        dbg.recorder._metrics_broken = True
        for i in range(20_000):  # many wraps of the default ring
            dbg.recorder.record(mn.STAGE_PUBLISH, i + 1.0,
                                trace_id=i, t1=i + 1.5)
        code, body = _request(srv.port, "/debug/trace")
        assert code == 200
        doc = json.loads(body)  # raises = endpoint served torn JSON
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert 0 < len(xs) <= dbg.recorder.capacity
        assert all(e["dur"] >= 0 for e in xs)

    def test_trace_bad_last_is_400(self, debug_srv):
        srv, _ = debug_srv()
        code, _ = _request(srv.port, "/debug/trace?last=bogus")
        assert code == 400

    def test_trace_post_is_405(self, debug_srv):
        srv, _ = debug_srv()
        code, _ = _request(srv.port, "/debug/trace", method="POST")
        assert code == 405

    def test_profile_get_is_405(self, debug_srv):
        srv, _ = debug_srv()
        code, _ = _request(srv.port, "/debug/profile")
        assert code == 405

    def test_profile_session_writes_artifacts(self, debug_srv):
        srv, dbg = debug_srv()
        code, body = _request(
            srv.port, "/debug/profile?seconds=0.1", method="POST"
        )
        assert code == 200, body
        doc = json.loads(body)
        assert doc["seconds"] == pytest.approx(0.1)
        assert os.path.isfile(
            os.path.join(doc["artifact_dir"], "threads.txt")
        )
        assert dbg.sessions == 1

    def test_profile_cooldown_503(self, debug_srv):
        srv, _ = debug_srv()
        code, _ = _request(
            srv.port, "/debug/profile?seconds=0.1", method="POST"
        )
        assert code == 200
        code, body = _request(
            srv.port, "/debug/profile?seconds=0.1", method="POST"
        )
        assert code == 503
        assert json.loads(body)["error"] == "cooldown"

    def test_profile_shedding_503(self, debug_srv):
        srv, _ = debug_srv(overload=_Overload(SHEDDING))
        code, body = _request(
            srv.port, "/debug/profile?seconds=0.1", method="POST"
        )
        assert code == 503
        assert json.loads(body)["error"] == "shedding"

    def test_thread_stacks_sees_main(self):
        stacks = thread_stacks()
        assert any("MainThread" in name for name in stacks)


# ------------------------------------- AOT disk cache (satellite fix)

class TestAotDiskCacheWarm:
    def test_second_telemetry_warm_all_hits(self, tmp_path):
        """BENCH_r06 regression (hits=1 misses=26): the snapshot /
        fleet-export / invertible-decode / flat-snapshot programs never
        consulted the disk cache. A second warm from the same cache dir
        must deserialize every program — zero fresh compiles."""
        import jax

        from retina_tpu.models.identity import IdentityMap
        from retina_tpu.models.pipeline import PipelineConfig
        from retina_tpu.parallel import (
            ShardedTelemetry, make_mesh, partition_events,
        )
        from retina_tpu.parallel.telemetry import aot_disk_cache_stats

        cfg = PipelineConfig(
            n_pods=1 << 4, cms_width=1 << 6, topk_slots=1 << 4,
            hll_precision=4, hll_pod_precision=4,
            entropy_buckets=1 << 6, conntrack_slots=1 << 6,
            latency_slots=1 << 4,
        )
        mesh = make_mesh(jax.devices())
        ident = IdentityMap.build_host({0x0A000001: 1}, n_slots=64)
        rec = np.zeros((64, 16), np.uint32)

        def warm():
            st = ShardedTelemetry(cfg, mesh,
                                  aot_cache_dir=str(tmp_path))
            state = st.init_state()
            sb = partition_events(rec, st.n_devices, capacity=64)
            state, _ = st.step(
                state, sb.records, sb.n_valid, np.uint32(1), ident
            )
            state, _ = st.end_window(state)
            st.snapshot(state, 1)
            st.fleet_export(state)
            st.inv_decode(state)
            st.snapshot_host(state, 1)

        s0 = aot_disk_cache_stats()
        warm()
        s1 = aot_disk_cache_stats()
        assert s1["misses"] - s0["misses"] >= 6, (s0, s1)
        assert s1["errors"] == s0["errors"], (s0, s1)

        warm()  # fresh ShardedTelemetry = restart: in-memory caches gone
        s2 = aot_disk_cache_stats()
        assert s2["misses"] - s1["misses"] == 0, (s1, s2)
        assert s2["errors"] == s1["errors"], (s1, s2)
        assert s2["hits"] - s1["hits"] >= 6, (s1, s2)
        # Per-program attribution: every regressed tag now hits.
        for tag in ("snapshot", "fleet_export", "inv_decode",
                    "snapshot_flat"):
            assert s2["by_tag"][tag]["hits"] >= 1, (tag, s2)

    def test_second_fold_warm_all_hits(self, tmp_path):
        """Same contract for the timetravel query programs (fold /
        extract), which live outside AotProgram."""
        import retina_tpu.timetravel.fold as fold
        from retina_tpu.parallel.telemetry import aot_disk_cache_stats

        fold.set_aot_cache_dir(str(tmp_path))
        try:
            slots = [
                {"flow_cms": np.ones((2, 32), np.uint32),
                 "hll_flows": np.ones((1, 16), np.uint8)}
                for _ in range(2)
            ]

            def warm():
                rf = fold.RangeFold()
                merged = rf.fold(slots, {"flow": 1, "hll_flows": 4})
                fold.range_extract(merged, {"flow": 1, "hll_flows": 4})

            s0 = aot_disk_cache_stats()
            warm()
            s1 = aot_disk_cache_stats()
            assert s1["misses"] - s0["misses"] >= 2, (s0, s1)

            fold._AOT_EXEC_CACHE.clear()  # simulate restart
            warm()
            s2 = aot_disk_cache_stats()
            assert s2["misses"] - s1["misses"] == 0, (s1, s2)
            assert s2["hits"] - s1["hits"] >= 2, (s1, s2)
            assert s2["by_tag"]["range_fold"]["hits"] >= 1
            assert s2["by_tag"]["range_extract"]["hits"] >= 1
        finally:
            fold.set_aot_cache_dir("")
            fold._AOT_EXEC_CACHE.clear()

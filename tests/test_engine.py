"""SketchEngine tests on the virtual 8-device CPU mesh (conftest.py):
feed→step→snapshot correctness vs exact numpy baselines, window/anomaly
closing, filter gating, checkpoint round-trip — the reference's pattern of
feeding synthetic flows and asserting metric outcomes (SURVEY.md §4)."""

import os
import threading
import time

import numpy as np
import pytest

from retina_tpu.config import Config
from retina_tpu.engine import SketchEngine
from retina_tpu.events.schema import (
    DIR_INGRESS,
    EV_FORWARD,
    F,
    NUM_FIELDS,
    OP_FROM_NETWORK,
    PROTO_TCP,
    VERDICT_FORWARDED,
)
from retina_tpu.events.synthetic import POD_NET, TrafficGen


def small_cfg(**kw) -> Config:
    cfg = Config()
    cfg.mesh_devices = kw.pop("mesh_devices", 2)
    cfg.batch_capacity = 1 << 10
    cfg.n_pods = 1 << 8
    cfg.cms_width = 1 << 10
    cfg.topk_slots = 1 << 7
    cfg.hll_precision = 8
    cfg.entropy_buckets = 1 << 8
    cfg.conntrack_slots = 1 << 10
    cfg.identity_slots = 1 << 10
    cfg.flush_interval_s = 0.01
    cfg.window_seconds = 0.2
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg


def mk_records(n, src_pods, dst_pods, verdict=VERDICT_FORWARDED, bytes_=100):
    rec = np.zeros((n, NUM_FIELDS), np.uint32)
    rec[:, F.SRC_IP] = POD_NET + np.asarray(src_pods, np.uint32)
    rec[:, F.DST_IP] = POD_NET + np.asarray(dst_pods, np.uint32)
    rec[:, F.PORTS] = (40000 << 16) | 80
    rec[:, F.META] = (
        (PROTO_TCP << 24) | (0x10 << 16) | (OP_FROM_NETWORK << 8)
        | (DIR_INGRESS << 4)
    )
    rec[:, F.BYTES] = bytes_
    rec[:, F.PACKETS] = 1
    rec[:, F.VERDICT] = verdict
    rec[:, F.EVENT_TYPE] = EV_FORWARD
    return rec


def test_engine_counts_match_exact():
    cfg = small_cfg()
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 50)})
    eng.compile()
    # 3 batches: pod 7 receives 300 ingress packets of 100 bytes
    for _ in range(3):
        eng.step_records(mk_records(100, src_pods=np.arange(100) % 49 + 1,
                                    dst_pods=np.full(100, 7)))
    snap = eng.snapshot(max_age_s=0)
    assert snap["totals"][0] == 300  # events
    assert snap["totals"][1] == 300  # forwarded packets
    # pod 7 ingress packets/bytes (dense rectangle, dir 0 = ingress)
    assert snap["pod_forward"][7, 0, 0] == 300
    assert snap["pod_forward"][7, 0, 1] == 30000


def test_engine_feed_loop_and_window():
    cfg = small_cfg()
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    stop = threading.Event()
    t = threading.Thread(target=eng.start, args=(stop,), daemon=True)
    t.start()
    assert eng.started.wait(2.0)
    gen = TrafficGen(n_flows=500, n_pods=16, seed=3)
    for _ in range(5):
        eng.sink.write_records(gen.batch(500), "test")
        time.sleep(0.05)
    time.sleep(0.5)  # at least one window close at 0.2s cadence
    stop.set()
    t.join(3.0)
    snap = eng.snapshot(max_age_s=0)
    assert snap["totals"][0] == 2500
    assert "entropy_bits" in eng.last_window
    assert eng.last_window["entropy_bits"].shape == (3,)


def test_engine_heavy_hitters_recall():
    cfg = small_cfg()
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 64)})
    eng.compile()
    gen = TrafficGen(n_flows=2000, n_pods=32, seed=11, drop_fraction=0,
                     dns_fraction=0)
    for _ in range(10):
        eng.step_records(gen.batch(2000))
    keys, counts = eng.top_flows(k=10)
    assert len(keys) == 10
    assert counts[0] >= counts[-1]
    # The generator's true hottest flow must appear in the sketch top-10
    # with roughly its true count.
    true = gen.true_counts()
    top_true = true.max()
    assert counts[0] >= 0.5 * top_true


def test_engine_filter_gates_unknown_endpoints():
    cfg = small_cfg()
    cfg.bypass_lookup_ip_of_interest = False
    cfg.enable_pod_level = True
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + 1: 1})  # only pod 1 known
    eng.compile()
    rec_known = mk_records(50, src_pods=np.full(50, 99),  # unknown src
                           dst_pods=np.full(50, 1))  # known dst
    rec_unknown = mk_records(70, src_pods=np.full(70, 88),
                             dst_pods=np.full(70, 77))  # both unknown
    eng.step_records(np.concatenate([rec_known, rec_unknown]))
    snap = eng.snapshot(max_age_s=0)
    assert snap["totals"][0] == 50  # unknown-both events filtered out
    # Explicit filter map admits an otherwise-unknown IP:
    eng.update_filter_ips({int(POD_NET + 88)})
    eng.step_records(rec_unknown)
    snap = eng.snapshot(max_age_s=0)
    assert snap["totals"][0] == 120


def test_engine_checkpoint_roundtrip(tmp_path):
    cfg = small_cfg()
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + 1: 1})
    eng.compile()
    eng.step_records(mk_records(100, np.full(100, 2), np.full(100, 1)))
    path = str(tmp_path / "state.npz")
    eng.save_snapshot_state(path)

    eng2 = SketchEngine(cfg)
    assert eng2.load_snapshot_state(path) is True
    snap = eng2.snapshot(max_age_s=0)
    assert snap["totals"][0] == 100
    assert snap["pod_forward"][1, 0, 0] == 100

    # Config mismatch: crash-only contract — never raises, quarantines
    # the stale checkpoint to .bad and cold-starts clean.
    cfg3 = small_cfg(cms_width=1 << 9)
    eng3 = SketchEngine(cfg3)
    assert eng3.load_snapshot_state(path) is False
    assert not os.path.exists(path)
    assert os.path.exists(path + ".bad")
    snap3 = eng3.snapshot(max_age_s=0)
    assert snap3["totals"][0] == 0


def test_engine_drop_accounting_on_overflow():
    cfg = small_cfg(batch_capacity=1 << 7)  # tiny shards force overflow
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + 3: 3, POD_NET + 4: 4})
    eng.compile()
    # One hot connection: every record lands on ONE device shard (conn-
    # consistent partitioning), so shard capacity 128 drops the rest.
    rec = mk_records(1000, np.full(1000, 3), np.full(1000, 4))
    eng.step_records(rec)
    snap = eng.snapshot(max_age_s=0)
    assert snap["totals"][0] <= 128
    assert snap["totals"][7] == 1000 - int(snap["totals"][0])  # lost


def test_identity_churn_incremental():
    """2k-pod identity churn: a single pod event must be cheap (VERDICT
    r1 weak #5) — host-side delta in µs, not an O(table) rebuild."""
    import time as _time

    import jax.numpy as jnp

    eng = SketchEngine(small_cfg(identity_slots=1 << 12))
    full = {POD_NET + i: i for i in range(1, 2001)}
    t0 = _time.perf_counter()
    eng.update_identities(full)
    full_s = _time.perf_counter() - t0

    # One pod added: diff + single cuckoo insert + one upload.
    full[POD_NET + 5000] = 2001
    t0 = _time.perf_counter()
    eng.update_identities(full)
    delta_s = _time.perf_counter() - t0
    assert delta_s < max(0.25, full_s), (delta_s, full_s)

    got = np.asarray(
        eng.ident.lookup(
            jnp.asarray(np.array([POD_NET + 1, POD_NET + 5000], np.uint32))
        )
    )
    assert list(got) == [1, 2001]

    # One pod removed.
    del full[POD_NET + 7]
    eng.update_identities(full)
    got = np.asarray(
        eng.ident.lookup(jnp.asarray(np.array([POD_NET + 7], np.uint32)))
    )
    assert got[0] == 0


def test_identity_overwrite_at_full_load():
    """Re-indexing an existing IP must succeed at exactly 50% load (an
    overwrite consumes no slot), and an overfull reconcile must leave the
    engine's previous table fully intact (transactional)."""
    import jax.numpy as jnp

    from retina_tpu.models.identity import HostIdentityTable

    h = HostIdentityTable(n_slots=1 << 4)
    for i in range(1, 9):  # exactly n_slots//2 keys
        h.insert(0x0A000000 + i, i)
    h.insert(0x0A000001, 99)  # overwrite at full load: must not raise
    assert h.get(0x0A000001) == 99
    with pytest.raises(ValueError):
        h.insert(0x0B000000, 1)  # a genuinely new key does raise

    eng = SketchEngine(small_cfg(identity_slots=1 << 4))
    eng.update_identities({POD_NET + i: i for i in range(1, 9)})
    # Overfull reconcile: clamp-and-count, never crash (VERDICT r3 weak
    # #4). The deterministic (sorted) subset keeps the lowest IPs, so
    # the previously-tracked pods survive; the overflow is visible in
    # lost_table_entries{table="identity"}.
    from retina_tpu.metrics import get_metrics

    eng.update_identities({POD_NET + i: i for i in range(1, 40)})
    lost = get_metrics().lost_table_entries.labels(table="identity")
    assert lost._value.get() == 39 - 8
    got = np.asarray(
        eng.ident.lookup(
            jnp.asarray(np.array([POD_NET + 3, POD_NET + 30], np.uint32))
        )
    )
    assert got[0] == 3  # kept (inside the clamped subset)
    assert got[1] == 0  # dropped (outside capacity)


def test_filter_overflow_clamps_and_counts():
    """2x-capacity IPs-of-interest push: the agent clamps to capacity,
    counts the overflow in lost_table_entries{table="filter"}, and stays
    up (manager_linux.go:62-100 counts per-IP failures the same way) —
    no retry loop, no exception into the pubsub callback."""
    from retina_tpu.managers.filtermanager import FilterManager
    from retina_tpu.metrics import get_metrics

    eng = SketchEngine(small_cfg(identity_slots=1 << 4))  # capacity 8
    fm = FilterManager(apply_fn=eng.update_filter_ips)
    fm.add_ips([int(POD_NET + i) for i in range(1, 17)], "test", "r1")
    lost = get_metrics().lost_table_entries.labels(table="filter")
    assert lost._value.get() == 16 - 8
    # The lowest 8 IPs won the deterministic clamp and are active.
    import jax.numpy as jnp

    got = np.asarray(
        eng.filter_map.lookup(
            jnp.asarray(np.array([POD_NET + 1, POD_NET + 12], np.uint32))
        )
    )
    assert got[0] == 1 and got[1] == 0
    # The exposition carries the counter (scrape visibility).
    from retina_tpu.exporter import get_exporter

    assert b"lost_table_entries" in get_exporter().gather_text()


def test_snapshot_never_stalls_feed():
    """Scrape-during-ingest contract (BASELINE: <1s scrape at sustained
    ingest; VERDICT r1 weak #3): forced snapshots from a scrape thread
    must not stall feed dispatches — the state lock is held only across
    async dispatches, never a device round-trip."""
    cfg = small_cfg(batch_capacity=1 << 12)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 200)})
    eng.compile()
    gen = TrafficGen(n_flows=5000, n_pods=190, seed=1)
    batches = [gen.batch(4096) for _ in range(8)]

    def run_feeder(duration: float, scrape: bool) -> np.ndarray:
        gaps: list[float] = []
        end = time.monotonic() + duration
        stop = threading.Event()

        def scraper():
            while not stop.is_set():
                eng.snapshot(max_age_s=0.0)
                time.sleep(0.01)

        ts = threading.Thread(target=scraper, daemon=True)
        if scrape:
            ts.start()
        i = 0
        last = time.perf_counter()
        while time.monotonic() < end:
            eng.step_records(batches[i % 8])
            now = time.perf_counter()
            gaps.append(now - last)
            last = now
            i += 1
        stop.set()
        if scrape:
            ts.join(1.0)
        return np.array(gaps[3:])

    base = run_feeder(2.0, scrape=False)
    scraped = run_feeder(4.0, scrape=True)
    # Feed keeps moving under scrape pressure. Bounds are generous (CI
    # scheduler noise) — the contract they defend is "the state lock is
    # never held across a device round-trip", whose failure mode is feed
    # gaps of the full snapshot readback time on every scrape (p50 blow-
    # up), not a single straggler.
    assert scraped.max() < 2.0, f"max feed gap {scraped.max():.3f}s"
    assert np.median(scraped) < max(8 * np.median(base), 0.1), (
        np.median(scraped), np.median(base))


def test_jit_cache_stable_across_ragged_batches():
    """Ragged ingest (odd block sizes, partial final flush slices) must
    hit ONE compiled step — padding in partition_events keeps device
    shapes static (VERDICT r1 weak #9)."""
    cfg = small_cfg(batch_capacity=1 << 10)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + 1: 1})
    eng.compile()
    rng = np.random.default_rng(0)
    for n in [1, 17, 333, 1024, 1500, 2047, 4096, 5000]:
        eng.step_records(
            mk_records(n, rng.integers(1, 5, n), rng.integers(1, 5, n))
        )
    assert eng.sharded._step._cache_size() == 1


def test_idle_window_close_skips_device_and_clears_gauges():
    """An idle agent's window ticks must cost zero device round-trips,
    must clear (not latch) the anomaly gauges, and must resume real
    closes when traffic returns."""
    from retina_tpu.metrics import get_metrics

    eng = SketchEngine(small_cfg())
    eng.compile()
    eng.step_records(mk_records(100, np.full(100, 2), np.full(100, 1)))
    calls = {"n": 0}
    real = eng.sharded.end_window

    def counting(state, *a, **kw):
        calls["n"] += 1
        return real(state, *a, **kw)

    eng.sharded.end_window = counting
    eng._close_window()  # has traffic: closes on device
    assert calls["n"] == 1
    # Pretend the last window flagged, then go idle.
    m = get_metrics()
    m.anomaly_flag.labels(dimension="src_ip").set(1.0)
    eng._close_window()
    eng._close_window()
    assert calls["n"] == 1  # idle ticks: no device call
    # Publishes (including the idle zeroing) ride the harvest queue in
    # close order; drain it before reading the gauges.
    eng._harvest_window()
    assert m.anomaly_flag.labels(
        dimension="src_ip")._value.get() == 0.0  # cleared, not latched
    # Traffic resumes: the close runs again.
    eng.step_records(mk_records(10, np.full(10, 3), np.full(10, 1)))
    eng._close_window()
    assert calls["n"] == 2


@pytest.mark.parametrize(
    "depth,combine", [(0, False), (0, True), (2, True)]
)
def test_feed_pipeline_modes_agree(depth, combine):
    """Synchronous, combined-synchronous, and pipelined feeds all land the
    same events (combining is lossless; the dispatch thread preserves
    step/window ordering).

    Overload must be OFF: this is an exactness contract, and on a
    loaded CI host the controller can slip into SAMPLING mid-feed —
    the HT-rescale then makes totals an estimate, not 1600, and the
    pipelined case flakes."""
    cfg = small_cfg(feed_pipeline_depth=depth, host_combine=combine,
                    overload_enabled=False)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    stop = threading.Event()
    t = threading.Thread(target=eng.start, args=(stop,), daemon=True)
    t.start()
    assert eng.started.wait(2.0)
    gen = TrafficGen(n_flows=50, n_pods=16, seed=3)  # few flows: real RLE
    for _ in range(4):
        eng.sink.write_records(gen.batch(400), "test")
        time.sleep(0.03)
    # Generous: the pipelined variant needs several dispatch+harvest
    # round-trips and CI boxes stall for whole seconds under load.
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        if int(eng.snapshot(max_age_s=0)["totals"][0]) == 1600:
            break
        time.sleep(0.05)
    stop.set()
    t.join(5.0)
    assert not t.is_alive()
    snap = eng.snapshot(max_age_s=0)
    assert int(snap["totals"][0]) == 1600
    assert int(snap["totals"][1]) == int(
        np.asarray(snap["pod_forward"])[:, :, 0].sum()
    )


def test_pipelined_window_close_ordered_with_steps():
    """A window close queued after steps must observe those steps'
    entropy contributions (ordering through the dispatch queue)."""
    cfg = small_cfg(feed_pipeline_depth=2, window_seconds=10.0)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    stop = threading.Event()
    t = threading.Thread(target=eng.start, args=(stop,), daemon=True)
    t.start()
    assert eng.started.wait(2.0)
    gen = TrafficGen(n_flows=200, n_pods=16, seed=5)
    eng.sink.write_records(gen.batch(1000), "test")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if int(eng.snapshot(max_age_s=0)["totals"][0]) == 1000:
            break
        time.sleep(0.05)
    # close directly while the engine is live (its loop window is 10s
    # so it never fired): entropy of the fed window must be non-zero —
    # steps preceded the close. The readback publishes on the harvest
    # thread; drain it explicitly. Must run BEFORE stop: engine
    # shutdown retires the harvest thread.
    eng._close_window()
    eng._harvest_window()
    stop.set()
    t.join(5.0)
    assert float(eng.last_window["entropy_bits"][0]) > 0.0


@pytest.mark.filterwarnings(
    # The injected fatal error escaping the worker thread IS the test.
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_dead_dispatch_worker_drops_and_counts(monkeypatch):
    """Failure injection for the dead-worker path (SURVEY §5.3): a
    dispatch worker killed by a fatal error escaping its loop must not
    wedge the feed loop — submissions drop with packet-weighted
    lost_events accounting and the engine keeps running."""
    from retina_tpu.engine import SketchEngine as Eng
    from retina_tpu.exporter import reset_for_tests as reset_exporter
    from retina_tpu.metrics import get_metrics, reset_for_tests

    reset_exporter()
    reset_for_tests()

    def fatal_loop(self, q):  # simulates a runtime error escaping
        raise RuntimeError("injected fatal dispatch error")

    monkeypatch.setattr(Eng, "_dispatch_loop", fatal_loop)
    cfg = small_cfg(feed_pipeline_depth=2, flush_interval_s=0.01)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    stop = threading.Event()
    t = threading.Thread(target=eng.start, args=(stop,), daemon=True)
    t.start()
    assert eng.started.wait(2.0)
    gen = TrafficGen(n_flows=100, n_pods=16, seed=5)
    fed = 0
    for _ in range(6):
        eng.sink.write_records(gen.batch(400), "test")
        fed += 400
        time.sleep(0.05)
    time.sleep(0.3)
    assert t.is_alive(), "feed loop must survive a dead worker"
    stop.set()
    t.join(3.0)
    assert not t.is_alive()
    lost = get_metrics().lost_events.labels(
        stage="dispatch", plugin="engine"
    )._value.get()
    # Sink losses (if the bounded sink overflowed) are counted at a
    # different stage; everything the feed loop flushed must land in
    # the dispatch-stage counter, packet-weighted.
    sink_lost = get_metrics().lost_events.labels(
        stage="sink", plugin="test"
    )._value.get()
    assert lost > 0
    assert lost + sink_lost >= fed * 0.5, (lost, sink_lost, fed)


def test_table_update_enqueued_before_dispatch_is_visible():
    """FIFO-visibility invariant for identity/filter tables: an update
    whose proxied upload is ENQUEUED before a batch executes must be
    applied to that batch — even when earlier proxy work delays the
    queue by seconds. Regression for the r5 race where dispatch-build
    captured the tables and a one-shot burst right after a pod
    registration was silently dropped by the stale (empty) filter."""
    from retina_tpu.utils import device_proxy
    from retina_tpu.utils.device_proxy import submit_on_device

    cfg = small_cfg(bypass_lookup_ip_of_interest=False)
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    # Park the proxy: everything enqueued behind this sleeper waits,
    # simulating a background-warm compile occupying the queue.
    submit_on_device(time.sleep, 3.0)
    # Deterministic ordering: spy on the proxy queue for the update's
    # apply_filter closure landing in it, then dispatch — the batch is
    # then PROVABLY enqueued after the table upload.
    enqueued = threading.Event()
    orig_put = device_proxy._q.put

    def spy_put(item, *a, **kw):
        fn = item[0]
        if getattr(fn, "__qualname__", "").endswith("apply_filter"):
            enqueued.set()
        return orig_put(item, *a, **kw)

    device_proxy._q.put = spy_put
    try:
        # Enqueue the filter update BEHIND the sleeper (blocks its
        # caller until applied, so it runs on a side thread).
        t = threading.Thread(
            target=eng.update_filter_ips, args=({POD_NET + 7},),
            daemon=True,
        )
        t.start()
        assert enqueued.wait(2.5), "filter update never enqueued"
    finally:
        device_proxy._q.put = orig_put
    # Dispatch a one-shot burst to the now-interesting pod. Enqueued
    # after the filter upload -> must see it, not the empty pre-update
    # map (which drops everything when bypass is off).
    eng.step_records(mk_records(50, src_pods=np.full(50, 3),
                                dst_pods=np.full(50, 7)))
    t.join(10.0)
    snap = eng.snapshot(max_age_s=0)
    assert int(snap["totals"][0]) == 50, (
        "batch dispatched after a filter update was filtered by the "
        "stale map"
    )


def test_harvest_thread_retires_and_stays_retired():
    """Engine shutdown retires the window-harvest thread; a straggler
    close (e.g. a warm key racing stop) must not resurrect it — a
    parked resurrected thread pins the engine object graph forever."""
    cfg = small_cfg()
    eng = SketchEngine(cfg)
    eng.update_identities({POD_NET + i: i for i in range(1, 20)})
    eng.compile()
    stop = threading.Event()
    t = threading.Thread(target=eng.start, args=(stop,), daemon=True)
    t.start()
    assert eng.started.wait(2.0)
    eng.sink.write_records(mk_records(20, np.full(20, 2), np.full(20, 7)),
                           "test")
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and eng._events_in < 20:
        time.sleep(0.05)
    # A real close so the harvest thread exists before shutdown.
    eng._close_window()
    eng._harvest_window()
    stop.set()
    t.join(10.0)
    assert eng._harvest_retired
    old = eng._harvest_thread
    assert old is None or not old.is_alive()
    # Straggler after shutdown: must not spawn a fresh thread.
    eng._ensure_harvest_thread()
    assert eng._harvest_thread is old or not eng._harvest_thread.is_alive()

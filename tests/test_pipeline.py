"""End-to-end pipeline step tests: synthetic flows in, aggregates out.

Mirrors the reference's module tests (pkg/module/metrics/metrics_module
_test.go feeds flows through the module loop and asserts metric outcomes).
"""

import numpy as np
import jax.numpy as jnp
import pytest

from retina_tpu.events.synthetic import POD_NET
from test_engine import mk_records

from retina_tpu.events.schema import (
    F,
    EventBuilder,
    EV_DNS_REQ,
    EV_DROP,
    OP_TO_ENDPOINT,
    OP_TO_STACK,
    TCP_ACK,
    TCP_SYN,
    VERDICT_DROPPED,
    ip_to_u32,
)
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline


SMALL = PipelineConfig(
    n_pods=256,
    cms_width=1 << 12,
    topk_slots=1 << 8,
    hll_precision=8,
    hll_pod_precision=6,
    entropy_buckets=1 << 8,
    conntrack_slots=1 << 10,
    latency_slots=1 << 8,
)


def _run(events_fn, ident=None, config=SMALL, capacity=512):
    pipe = TelemetryPipeline(config)
    state = pipe.init_state()
    builder = EventBuilder(capacity)
    events_fn(builder)
    step = pipe.jitted_step()
    ident = ident or IdentityMap.zeros(1 << 10)
    for batch in builder.drain():
        state, summary = step(
            state,
            jnp.asarray(batch.records),
            jnp.uint32(batch.n_valid),
            jnp.uint32(1000),
            ident,
            jnp.uint32(0),
        )
    return pipe, state, summary


def test_forward_counters_per_pod():
    pod_ip = ip_to_u32("10.0.0.5")
    ident = IdentityMap.build_host({pod_ip: 7}, 1 << 10)

    def gen(b):
        for _ in range(10):  # ingress to pod 7: 10 pkts, 1000 bytes
            b.add(src_ip=ip_to_u32("1.2.3.4"), dst_ip=pod_ip, bytes_=100,
                  obs_point=OP_TO_ENDPOINT)
        for _ in range(5):  # egress from pod 7
            b.add(src_ip=pod_ip, dst_ip=ip_to_u32("1.2.3.4"), bytes_=50,
                  obs_point=OP_TO_STACK)

    _, state, _ = _run(gen, ident)
    pf = np.asarray(state.pod_forward)
    assert pf[7, 0, 0] == 10 and pf[7, 0, 1] == 1000  # ingress pkts/bytes
    assert pf[7, 1, 0] == 5 and pf[7, 1, 1] == 250  # egress pkts/bytes
    nc = np.asarray(state.node_counters)
    assert nc[0, 0] == 10 and nc[1, 0] == 5


def test_drop_counters_by_reason():
    pod_ip = ip_to_u32("10.0.0.9")
    ident = IdentityMap.build_host({pod_ip: 3}, 1 << 10)

    def gen(b):
        for _ in range(4):
            b.add(src_ip=ip_to_u32("8.8.8.8"), dst_ip=pod_ip, bytes_=60,
                  obs_point=OP_TO_ENDPOINT, verdict=VERDICT_DROPPED,
                  drop_reason=2, event_type=EV_DROP)

    _, state, _ = _run(gen, ident)
    pd = np.asarray(state.pod_drop)
    assert pd[3, 2, 0] == 4 and pd[3, 2, 1] == 240
    assert np.asarray(state.totals)[2] == 4
    # Forward counters must NOT count drops.
    assert np.asarray(state.pod_forward)[3].sum() == 0


def test_tcpflags_counted():
    def gen(b):
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_SYN)
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_SYN | TCP_ACK)
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_ACK)

    _, state, _ = _run(gen)
    ptf = np.asarray(state.pod_tcpflags)[0]  # unknown pod bucket
    assert ptf[1] == 2  # SYN bit set twice
    assert ptf[4] == 2  # ACK bit set twice


def test_dns_counters():
    def gen(b):
        for _ in range(3):
            b.add(src_ip=5, dst_ip=6, event_type=EV_DNS_REQ,
                  dns=(1 << 16), dns_qhash=0xABCD)

    _, state, _ = _run(gen)
    assert np.asarray(state.pod_dns)[0, 1, 0] == 3
    assert np.asarray(state.totals)[3] == 3
    keys, counts = state.dns_hh.table.top_k_host(1)
    assert int(keys[0][0]) == 0xABCD and counts[0] == 3


def test_flow_heavy_hitter_found():
    hot = (ip_to_u32("10.1.1.1"), ip_to_u32("10.2.2.2"))

    def gen(b):
        rng = np.random.default_rng(0)
        for i in range(200):
            b.add(src_ip=hot[0], dst_ip=hot[1], src_port=5000, dst_port=80)
        for i in range(100):
            b.add(src_ip=int(rng.integers(1, 2**31)), dst_ip=int(rng.integers(1, 2**31)),
                  src_port=1234, dst_port=80)

    _, state, _ = _run(gen)
    keys, counts = state.flow_hh.table.top_k_host(1)
    assert int(keys[0][0]) == hot[0] and int(keys[0][1]) == hot[1]
    assert counts[0] >= 200


def test_service_graph_requires_known_pods():
    a, bip = ip_to_u32("10.0.0.1"), ip_to_u32("10.0.0.2")
    ident = IdentityMap.build_host({a: 1, bip: 2}, 1 << 10)

    def gen(b):
        for _ in range(50):
            b.add(src_ip=a, dst_ip=bip)
        for _ in range(60):  # unknown src -> not in service graph
            b.add(src_ip=ip_to_u32("99.9.9.9"), dst_ip=bip)

    _, state, _ = _run(gen, ident)
    keys, counts = state.svc_hh.table.top_k_host(5)
    assert len(keys) == 1  # only the known pod pair
    assert (int(keys[0][0]), int(keys[0][1])) == (1, 2) and counts[0] == 50


def test_entropy_window_and_anomaly_cycle():
    def gen(b):
        rng = np.random.default_rng(2)
        for _ in range(300):
            b.add(src_ip=int(rng.integers(1, 2**31)), dst_ip=7)

    pipe, state, _ = _run(gen)
    state, out = pipe.jitted_end_window()(state)
    assert float(out["entropy_bits"][0]) > 6.0  # diverse srcs
    assert float(out["entropy_bits"][1]) < 0.1  # single dst
    # Window reset: histograms cleared.
    assert float(np.asarray(state.entropy.counts).sum()) == 0


def test_totals_and_conntrack_reports():
    def gen(b):
        for i in range(20):
            b.add(src_ip=1, dst_ip=2, src_port=99, dst_port=80,
                  tcp_flags=TCP_ACK, ts_ns=10**9)

    _, state, summary = _run(gen)
    t = np.asarray(state.totals)
    assert t[0] == 20  # events
    # One connection, first sighting in batch -> exactly 1 conntrack report.
    assert t[6] == 1


def test_data_aggregation_level_low_gates_sketches():
    """data_aggregation_level wiring (reference config.go:16-23 compiled
    into the datapath at packetparser.c:214-225): at low, sketches grow
    only on conntrack reports (weighted by accumulated packets); dense
    rectangles stay exact per-packet in both modes."""
    import dataclasses as _dc

    from retina_tpu.models.identity import IdentityMap

    base = PipelineConfig(
        n_pods=64, cms_width=1 << 10, topk_slots=1 << 6,
        conntrack_slots=1 << 10, latency_slots=1 << 6,
        entropy_buckets=1 << 8, hll_precision=8,
    )
    ident = IdentityMap.build_host({POD_NET + i: i for i in (1, 2)},
                                   n_slots=1 << 8)
    # One steady connection pod1->pod2, 64 ACK events per batch, batches
    # 1 second apart (within the 30s report interval after the first).
    rec = mk_records(64, src_pods=np.full(64, 1), dst_pods=np.full(64, 2))

    def run(level):
        cfg = _dc.replace(base, data_aggregation_level=level)
        pipe = TelemetryPipeline(cfg)
        step = pipe.jitted_step()
        state = pipe.init_state()
        for t in range(3):
            state, _ = step(
                state, jnp.asarray(rec), jnp.uint32(64),
                jnp.uint32(100 + t), ident, jnp.uint32(0),
            )
        keys, counts = state.flow_hh.table.top_k_host(4)
        return state, (int(counts[0]) if len(counts) else 0)

    state_hi, hh_hi = run("high")
    state_lo, hh_lo = run("low")
    # High: every forwarded packet counted (3 x 64). Low: only the first
    # batch's new-connection report counted (64 accumulated packets);
    # batches 2-3 are within the report interval.
    assert hh_hi == 192, hh_hi
    assert hh_lo == 64, hh_lo
    # Dense rectangles identical (exact in both modes).
    assert (
        np.asarray(state_hi.pod_forward) == np.asarray(state_lo.pod_forward)
    ).all()
    assert int(np.asarray(state_lo.totals)[0]) == 192

    # Config validation: low without conntrack is rejected.
    with pytest.raises(ValueError):
        _dc.replace(base, enable_conntrack=False,
                    data_aggregation_level="low")


def test_ct_totals_accounting():
    """ct_totals accumulates reported packets/bytes (two-limb u32)."""
    from retina_tpu.models.identity import IdentityMap

    cfg = PipelineConfig(
        n_pods=64, cms_width=1 << 10, topk_slots=1 << 6,
        conntrack_slots=1 << 10, latency_slots=1 << 6,
        entropy_buckets=1 << 8, hll_precision=8,
    )
    ident = IdentityMap.build_host({POD_NET + 1: 1}, n_slots=1 << 8)
    pipe = TelemetryPipeline(cfg)
    step = pipe.jitted_step()
    state = pipe.init_state()
    rec = mk_records(10, src_pods=np.full(10, 1), dst_pods=np.full(10, 2),
                     bytes_=100)
    # Batch 1: new connection reports immediately, carrying 10 pkts/1000B.
    state, _ = step(state, jnp.asarray(rec), jnp.uint32(10), jnp.uint32(5),
                    ident, jnp.uint32(0))
    ctt = np.asarray(state.ct_totals)
    assert ctt[0] == 10 and ctt[2] == 1000, ctt
    # Batch 2 within the interval: no report, totals unchanged.
    state, _ = step(state, jnp.asarray(rec), jnp.uint32(10), jnp.uint32(6),
                    ident, jnp.uint32(0))
    ctt = np.asarray(state.ct_totals)
    assert ctt[0] == 10 and ctt[2] == 1000, ctt


def test_sum64_exact_over_u32_wrap():
    from retina_tpu.models.pipeline import _sum64

    # Two ~3 GiB report values: plain u32 sum wraps; _sum64 must not.
    x = jnp.asarray(np.array([3_000_000_000, 3_000_000_000, 7, 0], np.uint64)
                    .astype(np.uint32))
    lo, hi = _sum64(x)
    total = int(lo) + (int(hi) << 32)
    assert total == 6_000_000_007, total
    # Random fuzz vs python bigint.
    rng = np.random.default_rng(1)
    for _ in range(3):
        v = rng.integers(0, 2**32, 4096, dtype=np.uint64).astype(np.uint32)
        lo, hi = _sum64(jnp.asarray(v))
        assert int(lo) + (int(hi) << 32) == int(v.astype(object).sum())


def test_preaggregated_packets_column_consistent_low_high():
    """A record with PACKETS=N contributes N in BOTH aggregation modes
    (conntrack accumulates the packets column, not row counts)."""
    import dataclasses as _dc

    cfg = PipelineConfig(
        n_pods=64, cms_width=1 << 10, topk_slots=1 << 6,
        conntrack_slots=1 << 10, latency_slots=1 << 6,
        entropy_buckets=1 << 8, hll_precision=8,
    )
    ident = IdentityMap.build_host({POD_NET + 1: 1}, n_slots=1 << 8)
    rec = mk_records(8, src_pods=np.full(8, 1), dst_pods=np.full(8, 2))
    rec[:, F.PACKETS] = 50  # pre-aggregated 50 packets per record

    def hh(level):
        pipe = TelemetryPipeline(
            _dc.replace(cfg, data_aggregation_level=level)
        )
        state = pipe.init_state()
        state, _ = pipe.jitted_step()(
            state, jnp.asarray(rec), jnp.uint32(8), jnp.uint32(5),
            ident, jnp.uint32(0),
        )
        _, counts = state.flow_hh.table.top_k_host(2)
        return int(counts[0])

    assert hh("high") == 400
    assert hh("low") == 400  # new conn -> immediate report carrying 400

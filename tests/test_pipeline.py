"""End-to-end pipeline step tests: synthetic flows in, aggregates out.

Mirrors the reference's module tests (pkg/module/metrics/metrics_module
_test.go feeds flows through the module loop and asserts metric outcomes).
"""

import numpy as np
import jax.numpy as jnp

from retina_tpu.events.schema import (
    EventBuilder,
    EV_DNS_REQ,
    EV_DROP,
    OP_TO_ENDPOINT,
    OP_TO_STACK,
    TCP_ACK,
    TCP_SYN,
    VERDICT_DROPPED,
    ip_to_u32,
)
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline


SMALL = PipelineConfig(
    n_pods=256,
    cms_width=1 << 12,
    topk_slots=1 << 8,
    hll_precision=8,
    hll_pod_precision=6,
    entropy_buckets=1 << 8,
    conntrack_slots=1 << 10,
    latency_slots=1 << 8,
)


def _run(events_fn, ident=None, config=SMALL, capacity=512):
    pipe = TelemetryPipeline(config)
    state = pipe.init_state()
    builder = EventBuilder(capacity)
    events_fn(builder)
    step = pipe.jitted_step()
    ident = ident or IdentityMap.zeros(1 << 10)
    for batch in builder.drain():
        state, summary = step(
            state,
            jnp.asarray(batch.records),
            jnp.uint32(batch.n_valid),
            jnp.uint32(1000),
            ident,
            jnp.uint32(0),
        )
    return pipe, state, summary


def test_forward_counters_per_pod():
    pod_ip = ip_to_u32("10.0.0.5")
    ident = IdentityMap.build_host({pod_ip: 7}, 1 << 10)

    def gen(b):
        for _ in range(10):  # ingress to pod 7: 10 pkts, 1000 bytes
            b.add(src_ip=ip_to_u32("1.2.3.4"), dst_ip=pod_ip, bytes_=100,
                  obs_point=OP_TO_ENDPOINT)
        for _ in range(5):  # egress from pod 7
            b.add(src_ip=pod_ip, dst_ip=ip_to_u32("1.2.3.4"), bytes_=50,
                  obs_point=OP_TO_STACK)

    _, state, _ = _run(gen, ident)
    pf = np.asarray(state.pod_forward)
    assert pf[7, 0, 0] == 10 and pf[7, 0, 1] == 1000  # ingress pkts/bytes
    assert pf[7, 1, 0] == 5 and pf[7, 1, 1] == 250  # egress pkts/bytes
    nc = np.asarray(state.node_counters)
    assert nc[0, 0] == 10 and nc[1, 0] == 5


def test_drop_counters_by_reason():
    pod_ip = ip_to_u32("10.0.0.9")
    ident = IdentityMap.build_host({pod_ip: 3}, 1 << 10)

    def gen(b):
        for _ in range(4):
            b.add(src_ip=ip_to_u32("8.8.8.8"), dst_ip=pod_ip, bytes_=60,
                  obs_point=OP_TO_ENDPOINT, verdict=VERDICT_DROPPED,
                  drop_reason=2, event_type=EV_DROP)

    _, state, _ = _run(gen, ident)
    pd = np.asarray(state.pod_drop)
    assert pd[3, 2, 0] == 4 and pd[3, 2, 1] == 240
    assert np.asarray(state.totals)[2] == 4
    # Forward counters must NOT count drops.
    assert np.asarray(state.pod_forward)[3].sum() == 0


def test_tcpflags_counted():
    def gen(b):
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_SYN)
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_SYN | TCP_ACK)
        b.add(src_ip=1, dst_ip=2, tcp_flags=TCP_ACK)

    _, state, _ = _run(gen)
    ptf = np.asarray(state.pod_tcpflags)[0]  # unknown pod bucket
    assert ptf[1] == 2  # SYN bit set twice
    assert ptf[4] == 2  # ACK bit set twice


def test_dns_counters():
    def gen(b):
        for _ in range(3):
            b.add(src_ip=5, dst_ip=6, event_type=EV_DNS_REQ,
                  dns=(1 << 16), dns_qhash=0xABCD)

    _, state, _ = _run(gen)
    assert np.asarray(state.pod_dns)[0, 1, 0] == 3
    assert np.asarray(state.totals)[3] == 3
    keys, counts = state.dns_hh.table.top_k_host(1)
    assert int(keys[0][0]) == 0xABCD and counts[0] == 3


def test_flow_heavy_hitter_found():
    hot = (ip_to_u32("10.1.1.1"), ip_to_u32("10.2.2.2"))

    def gen(b):
        rng = np.random.default_rng(0)
        for i in range(200):
            b.add(src_ip=hot[0], dst_ip=hot[1], src_port=5000, dst_port=80)
        for i in range(100):
            b.add(src_ip=int(rng.integers(1, 2**31)), dst_ip=int(rng.integers(1, 2**31)),
                  src_port=1234, dst_port=80)

    _, state, _ = _run(gen)
    keys, counts = state.flow_hh.table.top_k_host(1)
    assert int(keys[0][0]) == hot[0] and int(keys[0][1]) == hot[1]
    assert counts[0] >= 200


def test_service_graph_requires_known_pods():
    a, bip = ip_to_u32("10.0.0.1"), ip_to_u32("10.0.0.2")
    ident = IdentityMap.build_host({a: 1, bip: 2}, 1 << 10)

    def gen(b):
        for _ in range(50):
            b.add(src_ip=a, dst_ip=bip)
        for _ in range(60):  # unknown src -> not in service graph
            b.add(src_ip=ip_to_u32("99.9.9.9"), dst_ip=bip)

    _, state, _ = _run(gen, ident)
    keys, counts = state.svc_hh.table.top_k_host(5)
    assert len(keys) == 1  # only the known pod pair
    assert (int(keys[0][0]), int(keys[0][1])) == (1, 2) and counts[0] == 50


def test_entropy_window_and_anomaly_cycle():
    def gen(b):
        rng = np.random.default_rng(2)
        for _ in range(300):
            b.add(src_ip=int(rng.integers(1, 2**31)), dst_ip=7)

    pipe, state, _ = _run(gen)
    state, out = pipe.jitted_end_window()(state)
    assert float(out["entropy_bits"][0]) > 6.0  # diverse srcs
    assert float(out["entropy_bits"][1]) < 0.1  # single dst
    # Window reset: histograms cleared.
    assert float(np.asarray(state.entropy.counts).sum()) == 0


def test_totals_and_conntrack_reports():
    def gen(b):
        for i in range(20):
            b.add(src_ip=1, dst_ip=2, src_port=99, dst_port=80,
                  tcp_flags=TCP_ACK, ts_ns=10**9)

    _, state, summary = _run(gen)
    t = np.asarray(state.totals)
    assert t[0] == 20  # events
    # One connection, first sighting in batch -> exactly 1 conntrack report.
    assert t[6] == 1

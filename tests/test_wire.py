"""Packed wire format: roundtrip fidelity host->device (parallel/wire.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.parallel.wire import (
    PACKED_FIELDS,
    pack_records,
    unpack_records_device,
    unpack_records_numpy,
)


def test_roundtrip_exact_on_realistic_traffic():
    gen = TrafficGen(n_flows=5000, n_pods=64, seed=9)
    rec = gen.batch(4096)
    rec[:, F.IFINDEX] = np.arange(4096, dtype=np.uint32) % 100
    packed, lo, hi = pack_records(rec)
    assert packed.shape == (4096, PACKED_FIELDS)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out, rec)


def test_device_and_numpy_unpack_agree():
    gen = TrafficGen(n_flows=500, n_pods=16, seed=2)
    rec = gen.batch(512)
    packed, lo, hi = pack_records(rec)
    a = unpack_records_numpy(packed, lo, hi)
    b = np.asarray(
        unpack_records_device(
            jnp.asarray(packed), jnp.uint32(lo), jnp.uint32(hi)
        )
    )
    np.testing.assert_array_equal(a, b)


def test_sharded_layout_roundtrip():
    gen = TrafficGen(n_flows=100, n_pods=8, seed=4)
    rec = gen.batch(256).reshape(2, 128, NUM_FIELDS)
    packed, lo, hi = pack_records(rec)
    assert packed.shape == (2, 128, PACKED_FIELDS)
    np.testing.assert_array_equal(
        unpack_records_numpy(packed, lo, hi), rec
    )


def test_ts_carry_across_u32_boundary():
    rec = np.zeros((2, NUM_FIELDS), np.uint32)
    # base just below a 2^32 ns boundary; second row crosses it.
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 0xFFFFFF00, 5
    rec[1, F.TS_LO], rec[1, F.TS_HI] = 0x00000100, 6
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out[:, F.TS_LO], rec[:, F.TS_LO])
    np.testing.assert_array_equal(out[:, F.TS_HI], rec[:, F.TS_HI])


def test_saturation_of_narrow_lanes():
    rec = np.zeros((1, NUM_FIELDS), np.uint32)
    rec[0, F.VERDICT] = 1000
    rec[0, F.DROP_REASON] = 1 << 20
    rec[0, F.EVENT_TYPE] = 99
    rec[0, F.IFINDEX] = 1 << 30
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    assert out[0, F.VERDICT] == 7
    assert out[0, F.DROP_REASON] == 255
    assert out[0, F.EVENT_TYPE] == 15
    assert out[0, F.IFINDEX] == 0x1FFFF


def test_zero_timestamp_rows_keep_rel_zero():
    """A source that never stamps must round-trip to ts 0 exactly —
    NOT inherit the batch base timestamp, which would feed phantom
    values into the apiserver RTT latency matcher."""
    rec = np.zeros((3, NUM_FIELDS), np.uint32)
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 100, 1  # the only stamped row
    rec[1, F.SRC_IP] = 7  # unstamped real row
    packed, lo, hi = pack_records(rec)
    assert packed[1, 0] == 0 and packed[2, 0] == 0
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out, rec)  # exact, incl. unstamped
    dev = np.asarray(
        unpack_records_device(
            jnp.asarray(packed), jnp.uint32(lo), jnp.uint32(hi)
        )
    )
    np.testing.assert_array_equal(dev, rec)


def test_spread_beyond_u32_saturates():
    rec = np.zeros((2, NUM_FIELDS), np.uint32)
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 1, 0
    rec[1, F.TS_LO], rec[1, F.TS_HI] = 0, 2  # ~8.6 s later
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out[0], rec[0])
    # saturated: clamped to base + (2^32 - 2), not wrapped past it (the
    # +1 TS_REL bias that reserves 0 for "unstamped" costs one count of
    # representable spread)
    got = (int(out[1, F.TS_HI]) << 32) | int(out[1, F.TS_LO])
    assert got == ((0 << 32) | 1) + 0xFFFFFFFE

"""Packed wire format: roundtrip fidelity host->device (parallel/wire.py)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.parallel.wire import (
    DENSE_BY_BITS,
    DENSE_PK_BITS,
    PACKED_FIELDS,
    dense_known_rows,
    dense_known_unpack_device,
    dense_known_unpack_numpy,
    dense_row_bits,
    dense_words,
    pack_records,
    unpack_records_device,
    unpack_records_numpy,
)


def test_roundtrip_exact_on_realistic_traffic():
    gen = TrafficGen(n_flows=5000, n_pods=64, seed=9)
    rec = gen.batch(4096)
    rec[:, F.IFINDEX] = np.arange(4096, dtype=np.uint32) % 100
    packed, lo, hi = pack_records(rec)
    assert packed.shape == (4096, PACKED_FIELDS)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out, rec)


def test_device_and_numpy_unpack_agree():
    gen = TrafficGen(n_flows=500, n_pods=16, seed=2)
    rec = gen.batch(512)
    packed, lo, hi = pack_records(rec)
    a = unpack_records_numpy(packed, lo, hi)
    b = np.asarray(
        unpack_records_device(
            jnp.asarray(packed), jnp.uint32(lo), jnp.uint32(hi)
        )
    )
    np.testing.assert_array_equal(a, b)


def test_sharded_layout_roundtrip():
    gen = TrafficGen(n_flows=100, n_pods=8, seed=4)
    rec = gen.batch(256).reshape(2, 128, NUM_FIELDS)
    packed, lo, hi = pack_records(rec)
    assert packed.shape == (2, 128, PACKED_FIELDS)
    np.testing.assert_array_equal(
        unpack_records_numpy(packed, lo, hi), rec
    )


def test_ts_carry_across_u32_boundary():
    rec = np.zeros((2, NUM_FIELDS), np.uint32)
    # base just below a 2^32 ns boundary; second row crosses it.
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 0xFFFFFF00, 5
    rec[1, F.TS_LO], rec[1, F.TS_HI] = 0x00000100, 6
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out[:, F.TS_LO], rec[:, F.TS_LO])
    np.testing.assert_array_equal(out[:, F.TS_HI], rec[:, F.TS_HI])


def test_saturation_of_narrow_lanes():
    rec = np.zeros((1, NUM_FIELDS), np.uint32)
    rec[0, F.VERDICT] = 1000
    rec[0, F.DROP_REASON] = 1 << 20
    rec[0, F.EVENT_TYPE] = 99
    rec[0, F.IFINDEX] = 1 << 30
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    assert out[0, F.VERDICT] == 7
    assert out[0, F.DROP_REASON] == 255
    assert out[0, F.EVENT_TYPE] == 15
    assert out[0, F.IFINDEX] == 0x1FFFF


def test_zero_timestamp_rows_keep_rel_zero():
    """A source that never stamps must round-trip to ts 0 exactly —
    NOT inherit the batch base timestamp, which would feed phantom
    values into the apiserver RTT latency matcher."""
    rec = np.zeros((3, NUM_FIELDS), np.uint32)
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 100, 1  # the only stamped row
    rec[1, F.SRC_IP] = 7  # unstamped real row
    packed, lo, hi = pack_records(rec)
    assert packed[1, 0] == 0 and packed[2, 0] == 0
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out, rec)  # exact, incl. unstamped
    dev = np.asarray(
        unpack_records_device(
            jnp.asarray(packed), jnp.uint32(lo), jnp.uint32(hi)
        )
    )
    np.testing.assert_array_equal(dev, rec)


def test_spread_beyond_u32_saturates():
    rec = np.zeros((2, NUM_FIELDS), np.uint32)
    rec[0, F.TS_LO], rec[0, F.TS_HI] = 1, 0
    rec[1, F.TS_LO], rec[1, F.TS_HI] = 0, 2  # ~8.6 s later
    packed, lo, hi = pack_records(rec)
    out = unpack_records_numpy(packed, lo, hi)
    np.testing.assert_array_equal(out[0], rec[0])
    # saturated: clamped to base + (2^32 - 2), not wrapped past it (the
    # +1 TS_REL bias that reserves 0 for "unstamped" costs one count of
    # representable spread)
    got = (int(out[1, F.TS_HI]) << 32) | int(out[1, F.TS_LO])
    assert got == ((0 << 32) | 1) + 0xFFFFFFFE


# -- v4 dense known-row bitstream -------------------------------------
#
# Three implementations of one bit layout (numpy pack, native pack,
# device unpack) must agree bit-for-bit; the property test sweeps
# randomized field domains and dictionary widths, the golden frame
# below makes any layout change a loud, reviewed failure.


def _dense_batch(rng, n, id_bits):
    """Random rows whose PACKETS/BYTES fit the dense lanes (the
    escalation mask's invariant), ids spanning the full dictionary."""
    rows = rng.integers(
        0, 2**32, size=(n, NUM_FIELDS), dtype=np.uint32
    )
    rows[:, F.PACKETS] = rng.integers(
        0, 1 << DENSE_PK_BITS, n, dtype=np.uint32
    )
    rows[:, F.BYTES] = rng.integers(
        0, 1 << DENSE_BY_BITS, n, dtype=np.uint32
    )
    ids = rng.integers(0, 1 << id_bits, n, dtype=np.uint32)
    return rows, ids


def test_dense_pack_unpack_property():
    """Property: numpy pack -> {numpy, device} unpack round-trips
    (ids, packets, bytes) exactly, for every dictionary width in use,
    ragged row counts (word-boundary straddles included), and lane
    extremes."""
    rng = np.random.default_rng(77)
    for id_bits in (12, 18, 21, 32):
        assert dense_row_bits(id_bits) <= 64
        for n in (0, 1, 2, 31, 32, 33, 257, 1000):
            rows, ids = _dense_batch(rng, n, id_bits)
            if n >= 2:  # pin lane extremes into every sized batch
                rows[0, F.PACKETS] = (1 << DENSE_PK_BITS) - 1
                rows[0, F.BYTES] = (1 << DENSE_BY_BITS) - 1
                ids[0] = (1 << id_bits) - 1 if id_bits < 32 else 0xFFFFFFFF
                rows[1, F.PACKETS] = 0
                rows[1, F.BYTES] = 0
                ids[1] = 0
            out = np.zeros(dense_words(n, id_bits), np.uint32)
            dense_known_rows(rows, ids, id_bits, out)
            gi, gp, gb = dense_known_unpack_numpy(out, n, id_bits)
            np.testing.assert_array_equal(gi, ids)
            np.testing.assert_array_equal(gp, rows[:, F.PACKETS])
            np.testing.assert_array_equal(gb, rows[:, F.BYTES])
            di, dp, db = dense_known_unpack_device(
                jnp.asarray(out), n, id_bits
            )
            np.testing.assert_array_equal(np.asarray(di), ids)
            np.testing.assert_array_equal(
                np.asarray(dp), rows[:, F.PACKETS]
            )
            np.testing.assert_array_equal(
                np.asarray(db), rows[:, F.BYTES]
            )


def test_dense_native_pack_bit_identical_to_numpy():
    """Native rt_flowwire_dense's known stream must be WORD-identical
    to the numpy pack (not merely unpack-equal): the device reader
    consumes raw words, so any spare-bit disagreement is format
    drift."""
    from retina_tpu.native import flowwire_dense_native

    rng = np.random.default_rng(31)
    for id_bits in (12, 18, 21):
        n = 777
        rows, ids = _dense_batch(rng, n, id_bits)
        rows[:, F.TS_LO] = rng.integers(1, 2**31, n)
        rows[:, F.TS_HI] = 0
        sel = (rng.random(n) < 0.3).astype(np.uint8)
        rows = np.ascontiguousarray(rows)
        n_sel = int(sel.sum())
        new_nat = np.zeros((n, 13), np.uint32)
        known_nat = np.zeros(
            dense_words(n - n_sel, id_bits), np.uint32
        )
        got = flowwire_dense_native(
            rows, ids, sel, 0, id_bits, DENSE_PK_BITS, DENSE_BY_BITS,
            new_nat, known_nat,
        )
        if got is None:
            import pytest

            pytest.skip("native library unavailable")
        assert got == n_sel
        keep = sel == 0
        known_ref = np.zeros_like(known_nat)
        dense_known_rows(rows[keep], ids[keep], id_bits, known_ref)
        np.testing.assert_array_equal(known_nat, known_ref)
        # New side unchanged from v3: id lane + the 12 packed lanes.
        packed12, _, _ = pack_records(rows[sel == 1], base=np.uint64(0))
        np.testing.assert_array_equal(new_nat[:n_sel, 0], ids[sel == 1])
        np.testing.assert_array_equal(new_nat[:n_sel, 1:], packed12)


def test_dense_golden_frame():
    """Golden frame: the committed word values ARE the v4 format. A
    failure here means the wire layout changed — bump the format
    deliberately (native ABI + this fixture together), never silently."""
    id_bits = 18
    ids = np.array([1, 0x3FFFF, 0x2A5A5, 7, 0x1F0F0], np.uint32)
    pk = np.array([1, 1023, 512, 3, 77], np.uint32)
    by = np.array(
        [40, (1 << 22) - 1, 0x200000, 1514, 0x12345], np.uint32
    )
    rows = np.zeros((5, NUM_FIELDS), np.uint32)
    rows[:, F.PACKETS] = pk
    rows[:, F.BYTES] = by
    out = np.zeros(dense_words(5, id_bits), np.uint32)
    dense_known_rows(rows, ids, id_bits, out)
    golden = np.array(
        [0x80040001, 0xFFFC0002, 0xFFFFFFFF, 0x802A5A5F, 0x01E00000,
         0x17A80300, 0x35F0F000, 0x00123451, 0x00000000],
        np.uint32,
    )
    np.testing.assert_array_equal(out, golden)
    gi, gp, gb = dense_known_unpack_numpy(golden, 5, id_bits)
    np.testing.assert_array_equal(gi, ids)
    np.testing.assert_array_equal(gp, pk)
    np.testing.assert_array_equal(gb, by)

"""Endurance soak harness (retina_tpu/soak/): schedule shapes +
validation, the preset cross-check (config.validate <-> synthetic
PRESETS <-> docs — the RT230 philosophy applied to traffic regimes),
sentinel verdicts over fabricated sample series, and a CI-sized
in-process soak through the real Daemon."""

import os
import sys

import pytest

from retina_tpu.config import Config
from retina_tpu.events.synthetic import MODES, PRESETS, TrafficGen
from retina_tpu.runtime import faults
from retina_tpu.soak.schedule import (
    SoakPhase, default_schedule, validate_schedule,
)
from retina_tpu.soak.sentinels import (
    SENTINELS, PhaseResult, Sample, evaluate_sentinels,
    rss_slope_mb_per_min,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ schedule

def test_smoke_schedule_shape():
    sch = default_schedule(60.0, smoke=True)
    assert len(sch) == 2
    assert sum(p.duration_s for p in sch) == pytest.approx(60.0)
    presets = {p.preset for p in sch}
    assert len(presets) == 2  # two distinct regimes
    faulted = [p for p in sch if p.fault_spec]
    assert len(faulted) == 1  # exactly one injected fault
    assert "press" in faulted[0].fault_spec


def test_full_schedule_rotation_and_repeat():
    sch = default_schedule(1800.0)
    assert len(sch) == 6
    assert sum(p.duration_s for p in sch) == pytest.approx(1800.0)
    # Heavy-tail coverage: every PSketch regime appears.
    presets = {p.preset for p in sch}
    for regime in ("dns_flood", "syn_storm", "conntrack_churn",
                   "elephant_mice"):
        assert regime in presets
    # Two press phases + one raise + one hang per rotation pass.
    assert sum(1 for p in sch if p.fault_spec) == 4
    # An hour repeats the same rotation: scorecards comparable.
    sch2 = default_schedule(3600.0)
    assert len(sch2) == 12
    assert [p.preset for p in sch2[:6]] == [p.preset for p in sch2[6:]]
    assert len({p.name for p in sch2}) == 12  # names stay unique


def test_validate_schedule_rejects():
    with pytest.raises(ValueError, match="empty"):
        validate_schedule([])
    with pytest.raises(ValueError, match="unknown preset"):
        validate_schedule([SoakPhase("x", "nosuch", 1.0)])
    with pytest.raises(ValueError, match="duration"):
        validate_schedule([SoakPhase("x", "zipf", 0.0)])
    with pytest.raises(ValueError, match="recovery_deadline"):
        validate_schedule(
            [SoakPhase("x", "zipf", 1.0, recovery_deadline_s=-1)]
        )
    # Fault specs are parsed by the REAL injector grammar.
    with pytest.raises(ValueError, match="bad fault spec"):
        validate_schedule(
            [SoakPhase("x", "zipf", 1.0, fault_spec="transfer:bogus")]
        )
    assert not faults.armed()  # the dry run always disarms


def test_validate_schedule_refuses_armed_layer():
    faults.configure("transfer:raise@1")
    try:
        with pytest.raises(RuntimeError, match="disarmed"):
            validate_schedule(
                [SoakPhase("x", "zipf", 1.0, fault_spec="harvest:raise")]
            )
    finally:
        faults.clear()


# ------------------------------------------- preset cross-check (RT230)

def test_presets_are_the_single_legal_source():
    """config.validate, the generator, and the docs must agree on the
    legal gen_preset names — the RT230 knob-drift philosophy applied
    to traffic regimes."""
    for name in PRESETS:
        Config(gen_preset=name).validate()  # every preset is legal
    with pytest.raises(ValueError, match="gen_preset"):
        Config(gen_preset="not_a_preset").validate()
    # Every mode a preset names is a mode the generator implements.
    for name, params in PRESETS.items():
        mode = params.get("mode", "mix")
        assert mode in MODES, f"preset {name!r} names unknown mode"
    # Docs row lists every preset by name.
    with open(os.path.join(REPO, "docs", "configuration.md")) as f:
        doc = f.read()
    for name in PRESETS:
        assert name in doc, f"preset {name!r} missing from docs"


@pytest.mark.parametrize("preset", sorted(PRESETS))
def test_every_preset_generates(preset):
    gen = TrafficGen(n_flows=64, n_pods=16, seed=1,
                     **{k: v for k, v in PRESETS[preset].items()})
    rec = gen.batch(256)
    assert rec.shape[0] == 256


# ------------------------------------------------------------ sentinels

def _sample(t, rss, **kw):
    d = dict(
        t=t, rss_mb=rss, events_in=int(t * 1000),
        windows_closed=float(t), overload_state="NOMINAL",
        pressure=0.0, fd_entries=100, fd_generation=0,
        recorder_spans=int(t * 10) + 1, recorder_enabled=True,
        aot_hits=5, aot_misses=2, aot_errors=0,
    )
    d.update(kw)
    return Sample(**d)


def _phase(name="p", fault="", closes=30.0, fd_delta=0,
           recovery=None, deadline=30.0, samples=None):
    return PhaseResult(
        name=name, preset="zipf", fault_spec=fault, duration_s=30.0,
        window_seconds=1.0,
        samples=samples or [_sample(0.0, 100.0), _sample(30.0, 100.0)],
        events_delta=10_000, closes_delta=closes,
        fd_generation_delta=fd_delta, recovery_seconds=recovery,
        recovery_deadline_s=deadline, stage_report={},
    )


def _verdict(verdicts, name):
    (v,) = [v for v in verdicts if v.sentinel == name]
    return v


def _eval(phases, samples, **kw):
    args = dict(rss_slope_bound_mb_per_min=5.0,
                fd_generations_per_phase=8,
                recorder_span_cost_us=4.0)
    args.update(kw)
    return evaluate_sentinels(phases, samples, **args)


def test_rss_slope_flat_vs_leak():
    flat = [_sample(t, 200.0 + (t % 3)) for t in range(0, 120, 2)]
    assert rss_slope_mb_per_min(flat) < 1.0
    # 0.5 MB/s leak = 30 MB/min — far over any sane bound.
    leaky = [_sample(t, 200.0 + 0.5 * t) for t in range(0, 120, 2)]
    assert rss_slope_mb_per_min(leaky) == pytest.approx(30.0, rel=0.05)
    ok = _verdict(_eval([_phase()], flat), "rss_flat")
    bad = _verdict(_eval([_phase()], leaky), "rss_flat")
    assert ok.ok and not bad.ok


def test_rss_slope_ignores_warmup_growth():
    # 100 MB of warmup growth in the first third, dead flat after:
    # the POST-warmup gate must pass.
    ramp = [_sample(t, 200.0 + min(t, 40) * 2.5) for t in range(0, 120, 2)]
    assert rss_slope_mb_per_min(ramp) < 5.0


def test_fd_churn_bound():
    vs = _eval([_phase(fd_delta=3), _phase(name="q", fd_delta=20)],
               [_sample(0, 100), _sample(60, 100)])
    v = _verdict(vs, "fd_churn")
    assert not v.ok and v.value == 20


def test_stalled_windows_floors():
    # Clean phase must close ~duration/window; fault phase only needs 1.
    healthy = _phase(closes=30.0)
    stalled = _phase(name="s", closes=2.0)
    faulted_slow = _phase(name="f", fault="transfer:raise@3", closes=1.0)
    faulted_dead = _phase(name="d", fault="harvest:hang", closes=0.0)
    samples = [_sample(0, 100), _sample(60, 100)]
    assert _verdict(_eval([healthy, faulted_slow], samples),
                    "stalled_windows").ok
    assert not _verdict(_eval([stalled], samples), "stalled_windows").ok
    assert not _verdict(_eval([faulted_dead], samples),
                        "stalled_windows").ok


def test_recorder_sentinel():
    samples = [_sample(0, 100), _sample(60, 100)]
    assert _verdict(_eval([_phase()], samples), "recorder").ok
    # Dead recorder (disabled or no spans) fails...
    dead = samples[:-1] + [_sample(60, 100, recorder_enabled=False)]
    assert not _verdict(_eval([_phase()], dead), "recorder").ok
    # ...and so does a degraded hot path, even with spans flowing.
    slow = _eval([_phase()], samples, recorder_span_cost_us=80.0)
    assert not _verdict(slow, "recorder").ok


def test_aot_cache_sentinel_late_misses():
    p1 = _phase(samples=[_sample(0, 100), _sample(30, 100, aot_misses=4)])
    # Misses frozen after phase 1 -> ok.
    steady = [_sample(0, 100),
              _sample(60, 100, aot_misses=4)]
    assert _verdict(_eval([p1, _phase(name="q")], steady),
                    "aot_cache").ok
    # New misses mid-soak = recompiles -> fail.
    drift = [_sample(0, 100), _sample(60, 100, aot_misses=9)]
    assert not _verdict(_eval([p1, _phase(name="q")], drift),
                        "aot_cache").ok
    # Any cache error fails regardless of misses.
    errs = [_sample(0, 100), _sample(60, 100, aot_errors=1)]
    assert not _verdict(_eval([p1, _phase(name="q")], errs),
                        "aot_cache").ok


def test_overload_recovery_sentinel():
    samples = [_sample(0, 100), _sample(60, 100)]
    fast = _phase(fault="transfer:raise@3", recovery=3.0, deadline=30.0)
    late = _phase(name="l", fault="harvest:hang2", recovery=45.0,
                  deadline=30.0)
    assert _verdict(_eval([fast], samples), "overload_recovery").ok
    assert not _verdict(_eval([late], samples), "overload_recovery").ok
    # Ending the soak outside NOMINAL = hysteresis latch-up.
    latched = _eval([fast], samples, final_overload_state="SHEDDING")
    assert not _verdict(latched, "overload_recovery").ok


def test_verdict_set_is_complete():
    vs = _eval([_phase()], [_sample(0, 100), _sample(60, 100)])
    assert tuple(v.sentinel for v in vs) == SENTINELS
    for v in vs:
        d = v.as_dict()
        assert {"sentinel", "ok", "value", "detail"} <= set(d)


# ------------------------------------------------- in-process CI soak

def test_run_soak_smoke_in_process(tmp_path):
    """A CI-sized soak through the REAL Daemon: two regimes, one
    bounded press fault, every sentinel sampled and the artifact
    written. Short phases cannot gate an MB/min RSS slope (warmup
    dominates), so that one bound is opened up — the 60s+ smoke in
    `make soak-smoke` holds the real default."""
    from retina_tpu.soak.runner import run_soak, soak_config

    cfg = soak_config(
        soak_artifact_dir=str(tmp_path),
        soak_rss_slope_mb_per_min=10_000.0,
    )
    sch = [
        SoakPhase("zipf_clean", "zipf", 3.5),
        SoakPhase("dns_press", "dns_flood", 3.5,
                  fault_spec="feed.backpressure:press1"),
    ]
    res = run_soak(cfg=cfg, schedule=sch,
                   log=lambda m: print(m, file=sys.stderr))
    assert res["ok"], res["sentinels"]
    assert set(res["sentinels"]) == set(SENTINELS)
    assert len(res["phases"]) == 2
    assert res["events_total"] > 0
    fault_phase = res["phases"][1]
    assert fault_phase["recovery_seconds"] is not None
    assert fault_phase["recovery_seconds"] <= 30.0
    assert os.path.basename(res["artifact"]).startswith("SOAK_")
    assert os.path.exists(res["artifact"])
    import json

    with open(res["artifact"]) as f:
        assert json.load(f)["ok"] is True


def test_run_soak_refuses_armed_fault_layer():
    from retina_tpu.soak.runner import run_soak

    faults.configure("transfer:raise@1")
    try:
        with pytest.raises(RuntimeError, match="armed"):
            run_soak(schedule=[SoakPhase("x", "zipf", 1.0)],
                     log=lambda m: None)
    finally:
        faults.clear()

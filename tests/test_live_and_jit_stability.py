"""VERDICT r1 weak #8/#9: the live AF_PACKET source exercised for real
(root + loopback), and a guard that ragged feed batches never grow the
engine's jit cache (a recompile per odd-sized flush would wreck the
feed-loop latency budget)."""

import os
import socket
import threading
import time

import numpy as np
import pytest

from retina_tpu.config import Config
from retina_tpu.engine import SketchEngine
from retina_tpu.events.schema import F, NUM_FIELDS
from retina_tpu.plugins.api import QueueSink
from retina_tpu.plugins.packetparser import PacketParserPlugin


def _can_af_packet() -> bool:
    if os.geteuid() != 0 or not hasattr(socket, "AF_PACKET"):
        return False
    try:
        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                          socket.htons(3))
        s.close()
        return True
    except OSError:
        return False


@pytest.mark.skipif(not _can_af_packet(),
                    reason="needs root + AF_PACKET (linux)")
def test_live_capture_decodes_loopback_udp():
    """Send real UDP datagrams over loopback; the live AF_PACKET source
    must capture and decode them into records with our 5-tuple."""
    cfg = Config()
    cfg.event_source = "live"
    cfg.capture_iface = "lo"
    plugin = PacketParserPlugin(cfg)
    plugin.generate()
    plugin.compile()
    plugin.init()
    sink = QueueSink()
    plugin.set_sink(sink)
    stop = threading.Event()
    t = threading.Thread(target=plugin.start, args=(stop,), daemon=True)
    t.start()
    try:
        time.sleep(0.3)  # capture loop warm
        tx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        tx.bind(("127.0.0.1", 0))
        src_port = tx.getsockname()[1]
        for i in range(20):
            tx.sendto(b"retina-live-%03d" % i, ("127.0.0.1", 15353))
            time.sleep(0.005)
        tx.close()

        deadline = time.monotonic() + 10
        ours = None
        while time.monotonic() < deadline and ours is None:
            for rec, plugin_name in sink.drain():
                assert plugin_name == "packetparser"
                match = rec[
                    (rec[:, F.PORTS] == ((src_port << 16) | 15353))
                    & (rec[:, F.SRC_IP] == 0x7F000001)
                ]
                if len(match):
                    ours = match
                    break
            time.sleep(0.1)
        assert ours is not None, "loopback UDP never decoded"
        # L3 length = 20 IP + 8 UDP + 15 payload.
        assert int(ours[0, F.BYTES]) == 43
        proto = int(ours[0, F.META]) >> 24
        assert proto == 17  # UDP
    finally:
        stop.set()
        plugin.stop()
        t.join(5)


# ---------------------------------------------------------------------
def small_cfg() -> Config:
    cfg = Config()
    cfg.mesh_devices = 2
    cfg.batch_capacity = 1 << 10
    cfg.n_pods = 1 << 8
    cfg.cms_width = 1 << 10
    cfg.topk_slots = 1 << 7
    cfg.hll_precision = 8
    cfg.entropy_buckets = 1 << 8
    cfg.conntrack_slots = 1 << 10
    cfg.identity_slots = 1 << 10
    return cfg


def test_ragged_batches_do_not_recompile():
    """partition_events pads every host block to (D, capacity, F), so
    the jit cache must hold exactly ONE entry no matter how ragged the
    flush sizes are — a recompile mid-feed would stall ingest for
    seconds (VERDICT r1 weak #9)."""
    eng = SketchEngine(small_cfg())
    eng.compile()

    def cache_sizes() -> dict[str, int]:
        return {
            name: fn._cache_size()
            for name, fn in (
                ("step", eng.sharded._step),
                ("end_window", eng.sharded._end_window),
            )
            if fn is not None
        }

    base = cache_sizes()
    assert base["step"] == 1, base

    cap = eng.cfg.batch_capacity
    rng = np.random.default_rng(7)
    # Ragged shapes: tiny, odd, full, just-past-full (engine splits),
    # and the final-partial-slice shape the feed loop produces.
    for n in (1, 7, 333, cap - 1, cap, cap // 2 + 13):
        rec = rng.integers(0, 2**31, size=(n, NUM_FIELDS),
                           dtype=np.int64).astype(np.uint32)
        eng.step_records(rec, now_s=1000)

    after = cache_sizes()
    assert after["step"] == 1, (
        f"jit cache grew: {base} -> {after}; a ragged batch changed the "
        f"traced shape"
    )

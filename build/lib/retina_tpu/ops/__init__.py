"""Device compute kernels: hashing and sketches.

These replace the reference's two aggregation tiers — kernel-side per-CPU
hash maps (e.g. drop_reason.c:88-94) and the single-threaded Go
``Module.run`` ProcessFlow loop (pkg/module/metrics/metrics_module.go:283-303,
the scaling bottleneck) — with jit-compiled vectorized kernels.
"""

from retina_tpu.ops.hashing import fmix32, hash_cols, hash_family, flow_key_hash64  # noqa: F401
from retina_tpu.ops.countmin import CountMinSketch  # noqa: F401

"""mockplugin: trivial fake plugin for wiring tests.

Reference analog: pkg/plugin/mockplugin — a no-op plugin used to test the
pluginmanager lifecycle without a kernel. This one records lifecycle calls
and can be told to fail at any stage or emit canned records.
"""

from __future__ import annotations

import threading

import numpy as np

from retina_tpu.config import Config
from retina_tpu.events.schema import NUM_FIELDS
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin


@registry.register
class MockPlugin(Plugin):
    name = "mock"

    fail_stage: str | None = None  # class-level test knob

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.calls: list[str] = []
        self.records_to_emit: np.ndarray | None = None
        self.started = threading.Event()

    def _maybe_fail(self, stage: str) -> None:
        self.calls.append(stage)
        if MockPlugin.fail_stage == stage:
            raise RuntimeError(f"mock failure at {stage}")

    def generate(self) -> None:
        self._maybe_fail("generate")

    def compile(self) -> None:
        self._maybe_fail("compile")

    def init(self) -> None:
        self._maybe_fail("init")

    def start(self, stop: threading.Event) -> None:
        self._maybe_fail("start")
        self.started.set()
        if self.records_to_emit is None:
            self.records_to_emit = np.zeros((4, NUM_FIELDS), np.uint32)
        while not stop.is_set():
            self.emit(self.records_to_emit)
            stop.wait(0.01)

    def stop(self) -> None:
        self.calls.append("stop")

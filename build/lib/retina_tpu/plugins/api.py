"""Plugin interface + event sink contracts.

Reference analog: pkg/plugin/registry/registry.go:16-34 — every plugin
implements ``Name/Generate/Compile/Init/Start/Stop/SetupChannel``. The TPU
mapping of the lifecycle:

- **generate**: produce derived config (the reference writes dynamic.h
  macros for eBPF, packetparser_linux.go:82-127; here plugins derive their
  static kernel shapes / source settings from Config).
- **compile**: build the compute (reference shells out to clang,
  pkg/loader/compile.go; here: jit-lower/warm the plugin's device code so
  Start never pays first-compile latency).
- **init**: allocate runtime state (reference loads BPF objects; here:
  device buffers / parsers / sockets).
- **start(stop_event)**: blocking feed loop until stop is set (reference
  plugin.Start(ctx) blocking goroutine).
- **stop**: idempotent teardown.
- **setup_channel(queue)**: hand the plugin an external event queue for
  the Hubble-style export path (registry.go:31-33); plugins that emit
  flows mirror them there, dropping (and counting) when full — never
  blocking, like packetparser_linux.go:645-651.

Events flow into an :class:`EventSink` — the seam the enricher/batcher
provides (the ``enricher.Write`` analog, enricher.go:185-187) — as numpy
record blocks, not per-event calls: batches are the unit the device wants.
"""

from __future__ import annotations

import abc
import queue as queue_mod
import threading
from typing import Optional, Protocol

import numpy as np

from retina_tpu.config import Config
from retina_tpu.log import logger


class UnsupportedPlatform(RuntimeError):
    """Raised by plugins that cannot run on this host OS."""


class EventSink(Protocol):
    """Where plugins write decoded event records."""

    def write_records(self, records: np.ndarray, plugin: str) -> int:
        """Append (N, NUM_FIELDS) uint32 rows. Returns rows accepted;
        short writes mean overflow (caller counts lost events)."""
        ...


class NullSink:
    """Discards everything (tests / disabled pipeline)."""

    def write_records(self, records: np.ndarray, plugin: str) -> int:
        return len(records)


class Plugin(abc.ABC):
    """Base plugin (reference registry.Plugin)."""

    name: str = ""

    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.log = logger(f"plugin.{self.name}")
        self.sink: EventSink = NullSink()
        self.external: Optional[queue_mod.Queue] = None
        self._external_lost = 0

    # -- lifecycle ---------------------------------------------------
    def generate(self) -> None:  # noqa: B027
        """Derive config (dynamic.h analog). Default: nothing."""

    def compile(self) -> None:  # noqa: B027
        """Warm jit caches / build parsers. Default: nothing."""

    def init(self) -> None:  # noqa: B027
        """Allocate runtime resources. Default: nothing."""

    @abc.abstractmethod
    def start(self, stop: threading.Event) -> None:
        """Blocking loop; must return promptly once ``stop`` is set."""

    def stop(self) -> None:  # noqa: B027
        """Idempotent teardown. Default: nothing."""

    # -- wiring ------------------------------------------------------
    def set_sink(self, sink: EventSink) -> None:
        self.sink = sink

    def setup_channel(self, q: queue_mod.Queue) -> None:
        """External (Hubble-path) queue (registry.go:31-33)."""
        self.external = q

    def emit(self, records: np.ndarray) -> int:
        """Write records to sink + mirror to external channel, never
        blocking; losses are counted (packetparser_linux.go:645-651).
        Returns rows the sink accepted so paced sources can yield
        instead of busy-spinning against a full sink."""
        if len(records) == 0:
            return 0
        accepted = self.sink.write_records(records, self.name)
        if accepted < len(records):
            self.count_lost("buffered", len(records) - accepted)
        if self.external is not None:
            try:
                self.external.put_nowait(records)
            except queue_mod.Full:
                self._external_lost += len(records)
                self.count_lost("external", len(records))
        return accepted

    def count_lost(self, stage: str, n: int) -> None:
        from retina_tpu.metrics import get_metrics

        get_metrics().lost_events.labels(stage=stage, plugin=self.name).inc(n)


class QueueSink:
    """Bounded sink over a queue of record blocks — the userspace record
    channel analog (10k-deep, drop-on-full; packetparser types_linux.go:38,
    packetparser_linux.go:692-697). The batcher drains it."""

    def __init__(self, max_blocks: int = 1024):
        self.q: queue_mod.Queue[tuple[np.ndarray, str]] = queue_mod.Queue(
            maxsize=max_blocks
        )

    def write_records(self, records: np.ndarray, plugin: str) -> int:
        try:
            self.q.put_nowait((records, plugin))
            return len(records)
        except queue_mod.Full:
            return 0

    def drain(self, max_blocks: int = 64) -> list[tuple[np.ndarray, str]]:
        out = []
        for _ in range(max_blocks):
            try:
                out.append(self.q.get_nowait())
            except queue_mod.Empty:
                break
        return out

"""infiniband: RDMA NIC counters.

Reference analog: pkg/plugin/infiniband — parses
``/sys/class/infiniband/*/ports/*/counters`` and per-interface debug
status params (infiniband_stats_linux.go). Identical here; on hosts
without InfiniBand hardware the sysfs tree is absent and the plugin idles
(the reference behaves the same).
"""

from __future__ import annotations

import threading

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.sources import procfs


@registry.register
class InfinibandPlugin(Plugin):
    name = "infiniband"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.sys_root = "/sys"

    def read_and_publish(self) -> None:
        m = get_metrics()
        for (dev, port), counters in procfs.read_infiniband_counters(
            self.sys_root
        ).items():
            for stat, v in counters.items():
                m.infiniband_counter_stats.labels(
                    device=dev, port=port, statistic_name=stat
                ).set(v)
        for iface, params in procfs.read_infiniband_status_params(
            self.sys_root
        ).items():
            for p, v in params.items():
                try:
                    m.infiniband_status_params.labels(
                        interface=iface, statistic_name=p
                    ).set(float(v))
                except ValueError:
                    continue  # non-numeric status param

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.read_and_publish()
            except Exception:
                self.log.exception("infiniband read failed")
            stop.wait(self.cfg.metrics_interval_s)

"""packetforward: node-level forwarded packet/byte counters.

Reference analog: pkg/plugin/packetforward — a BPF socket filter on eth0
counts {ingress,egress} × {packets,bytes} into a per-CPU map a Go ticker
reads as deltas (packetforward_linux.go, _cprog/packetforward.c:29-58).
Host analog: the kernel already keeps exactly these counters per NIC;
read ``psutil.net_io_counters`` deltas per MetricsInterval and publish the
same two gauge families.
"""

from __future__ import annotations

import threading

import psutil

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin


@registry.register
class PacketForwardPlugin(Plugin):
    name = "packetforward"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._prev: tuple[int, int, int, int] | None = None
        self._totals = [0, 0, 0, 0]  # in_pkts, out_pkts, in_bytes, out_bytes

    def _read(self) -> tuple[int, int, int, int]:
        io = psutil.net_io_counters(pernic=self.cfg.capture_iface != "")
        if self.cfg.capture_iface:
            io = io.get(self.cfg.capture_iface)
            if io is None:
                return (0, 0, 0, 0)
        return (io.packets_recv, io.packets_sent, io.bytes_recv, io.bytes_sent)

    def read_and_publish(self) -> None:
        cur = self._read()
        if self._prev is not None:
            # Publish cumulative deltas since plugin start (the reference
            # publishes running totals read from the map; counters reset
            # with the agent either way).
            for i in range(4):
                d = cur[i] - self._prev[i]
                if d > 0:
                    self._totals[i] += d
        self._prev = cur
        m = get_metrics()
        m.forward_count.labels(direction="ingress").set(self._totals[0])
        m.forward_count.labels(direction="egress").set(self._totals[1])
        m.forward_bytes.labels(direction="ingress").set(self._totals[2])
        m.forward_bytes.labels(direction="egress").set(self._totals[3])

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.read_and_publish()
            except Exception:
                self.log.exception("packetforward read failed")
            stop.wait(self.cfg.metrics_interval_s)

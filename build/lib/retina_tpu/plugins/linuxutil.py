"""linuxutil: host networking stats, no packet path.

Reference analog: pkg/plugin/linuxutil — a MetricsInterval ticker parses
``/proc/net/netstat`` + ``/proc/net/snmp`` (netstat_stats_linux.go:20-21)
and per-NIC ethtool counters (ethtool_stats_linux.go) into gauges, with an
LRU of NICs that don't support stats. Here the NIC counters come from
``/sys/class/net/*/statistics`` (same numbers, no ioctl) and virtual
interfaces are skipped like the reference skips unsupported ones.
"""

from __future__ import annotations

import threading

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.sources import procfs

# TCP state gauge comes from SNMP Tcp counters the kernel exposes.
_TCP_STATS = ("ActiveOpens", "PassiveOpens", "AttemptFails", "EstabResets",
              "CurrEstab", "InSegs", "OutSegs", "RetransSegs", "InErrs",
              "OutRsts")
_UDP_STATS = ("InDatagrams", "NoPorts", "InErrors", "OutDatagrams",
              "RcvbufErrors", "SndbufErrors")
_IP_STATS = ("InReceives", "InHdrErrors", "InAddrErrors", "ForwDatagrams",
             "InDiscards", "InDelivers", "OutRequests", "OutDiscards",
             "OutNoRoutes")
_IFACE_STATS = ("rx_bytes", "tx_bytes", "rx_packets", "tx_packets",
                "rx_errors", "tx_errors", "rx_dropped", "tx_dropped")


@registry.register
class LinuxUtilPlugin(Plugin):
    name = "linuxutil"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.proc_root = "/proc"
        self.sys_root = "/sys"
        self._unsupported: set[str] = set()  # LRU-of-unsupported analog

    def read_and_publish(self) -> None:
        m = get_metrics()
        snmp = procfs.read_snmp(self.proc_root)
        netstat = procfs.read_netstat(self.proc_root)
        tcp = {**snmp.get("Tcp", {}), **netstat.get("TcpExt", {})}
        for k in _TCP_STATS:
            if k in tcp:
                m.tcp_connection_stats.labels(statistic_name=k).set(tcp[k])
        udp = snmp.get("Udp", {})
        for k in _UDP_STATS:
            if k in udp:
                m.udp_connection_stats.labels(statistic_name=k).set(udp[k])
        ip = snmp.get("Ip", {})
        for k in _IP_STATS:
            if k in ip:
                m.ip_connection_stats.labels(statistic_name=k).set(ip[k])
        for iface, stats in procfs.read_iface_stats(self.sys_root).items():
            if iface in self._unsupported:
                continue
            if not any(stats.get(s) for s in _IFACE_STATS):
                self._unsupported.add(iface)  # idle/virtual NIC: skip forever
                continue
            for k in _IFACE_STATS:
                if k in stats:
                    m.interface_stats.labels(
                        interface_name=iface, statistic_name=k
                    ).set(stats[k])

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.read_and_publish()
            except Exception:
                self.log.exception("linuxutil read failed")
            stop.wait(self.cfg.metrics_interval_s)

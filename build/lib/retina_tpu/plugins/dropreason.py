"""dropreason: packet-drop accounting.

Reference analog: pkg/plugin/dropreason — kprobes on nf_hook_slow,
tcp_v4_connect, inet_csk_accept etc. fill a per-CPU metrics map (basic
mode) and a perf ring of drop events (advanced mode),
dropreason_linux.go:296-412. Host analog, same two modes:

- **basic**: a MetricsInterval ticker reads kernel drop counters the host
  actually exposes — softnet drops (/proc/net/softnet_stat) and TcpExt
  listen/overflow drops (/proc/net/netstat) — publishing the same
  drop_count/drop_bytes gauge family keyed by reason.
- **advanced**: drop-verdict events arriving from the packet sources flow
  through the device pipeline (pod_drop rectangles, HLL per-reason
  cardinality), exactly where the reference's perf-ring drop flows end up.
"""

from __future__ import annotations

import threading

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.sources import procfs

# Reason ids 1..7 used by synthetic/pcap sources map to the reference's
# drop reasons (dropreason kprobe sites); host-derived reasons use
# names. 8..13 carry Cilium dataplane reasons mapped by the
# ciliumeventobserver ingest (sources/cilium_monitor.py) — the reason
# axis is a bounded rectangle (n_drop_reasons=16), so Cilium's sparse
# 130+ id space folds into named buckets instead of clamping to 15.
DROP_REASONS = {
    0: "unknown",
    1: "iptable_rule_drop",
    2: "iptable_nat_drop",
    3: "tcp_connect_basic",
    4: "tcp_accept_basic",
    5: "conntrack_add_drop",
    6: "softnet_drop",
    7: "listen_overflow",
    8: "policy_denied",
    9: "invalid_packet",
    10: "invalid_source_ip",
    11: "conntrack_invalid",
    12: "unsupported_proto",
    13: "cilium_other",
}


@registry.register
class DropReasonPlugin(Plugin):
    name = "dropreason"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.proc_root = "/proc"
        self._base: dict[str, int] = {}

    def _read_host_drops(self) -> dict[str, int]:
        netstat = procfs.read_netstat(self.proc_root)
        tcpext = netstat.get("TcpExt", {})
        return {
            "softnet_drop": procfs.read_softnet_drops(self.proc_root),
            "listen_overflow": tcpext.get("ListenOverflows", 0)
            + tcpext.get("ListenDrops", 0),
            "tcp_accept_basic": tcpext.get("EmbryonicRsts", 0),
        }

    def init(self) -> None:
        self._base = self._read_host_drops()  # count from plugin start

    def read_and_publish(self) -> None:
        m = get_metrics()
        cur = self._read_host_drops()
        for reason, v in cur.items():
            delta = max(v - self._base.get(reason, 0), 0)
            m.drop_count.labels(reason=reason, direction="ingress").set(delta)

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.read_and_publish()
            except Exception:
                self.log.exception("dropreason read failed")
            stop.wait(self.cfg.metrics_interval_s)

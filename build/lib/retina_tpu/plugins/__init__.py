"""Data-plane plugins (reference pkg/plugin, SURVEY.md §2.2).

Importing this package registers every platform-supported plugin with the
registry (the reference's ``init()`` + ``registry.Add`` self-registration,
registry.go:42-47).
"""

import sys

from retina_tpu.plugins import registry
from retina_tpu.plugins.api import (
    EventSink,
    Plugin,
    QueueSink,
    UnsupportedPlatform,
)

# Self-registration imports (each module calls registry.add at import).
from retina_tpu.plugins import (  # noqa: F401
    ciliumeventobserver,
    conntrack_gc,
    dns,
    dropreason,
    externalevents,
    infiniband,
    linuxutil,
    mockplugin,
    packetforward,
    packetparser,
    tcpretrans,
)

# Registered on every platform: the collector/parser logic is
# cross-platform (and tested on Linux via injected sources); only the
# default OS sources are win32-gated, raising UnsupportedPlatform from
# init() elsewhere — which pluginmanager contains.
from retina_tpu.plugins import windows  # noqa: E402,F401

__all__ = [
    "EventSink",
    "Plugin",
    "QueueSink",
    "UnsupportedPlatform",
    "registry",
]

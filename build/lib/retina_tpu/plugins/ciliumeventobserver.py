"""ciliumeventobserver: ingest flows from a real Cilium dataplane.

Reference analog: pkg/plugin/ciliumeventobserver/ciliumeventobserver_linux.go
:49-200 — dial Cilium's monitor unix socket, gob-decode
``payload.Payload`` values, parse the embedded BPF perf events into
flows, and feed them to the enricher. Differences by design: the gob
decode is an incremental pure-Python codec (sources/gobcodec.py), the
perf-event headers parse into the shared record schema, and the embedded
packets batch-decode through the SAME vectorized packet decoder as every
other source (sources/cilium_monitor.py) — so Cilium-origin flows enter
the device pipeline as one more batched record stream, not a per-event
object path.

Wire-compat note: a generalized high-rate path for OTHER producers (our
own agents, replay tools) exists separately as ``externalevents``
(length-prefixed msgpack frames); THIS plugin speaks Cilium's actual
socket protocol so it can attach to an unmodified Cilium agent.
"""

from __future__ import annotations

import socket
import threading
import time

from retina_tpu.config import Config
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.sources.cilium_monitor import (
    PAYLOAD_EVENT_SAMPLE,
    PAYLOAD_RECORD_LOST,
    events_to_records,
    parse_perf_sample,
)
from retina_tpu.sources.gobcodec import GobError, GobStreamDecoder

# Reference constants (ciliumeventobserver_linux.go:24-29).
MAX_ATTEMPTS = 5
RETRY_DELAY_S = 12.0
BATCH_FRAMES = 2048  # flush the parsed-event batch at this size
BATCH_INTERVAL_S = 0.05  # ...or this age, whichever first


@registry.register
class CiliumEventObserverPlugin(Plugin):
    name = "ciliumeventobserver"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self._retry_delay = RETRY_DELAY_S
        self._max_attempts = MAX_ATTEMPTS

    def generate(self) -> None:
        if not self.cfg.monitor_sock_path:
            raise ValueError(
                "ciliumeventobserver: monitor_sock_path not set"
            )

    def _connect(self, stop: threading.Event) -> socket.socket | None:
        """Dial with bounded retry (reference connect(), :130-152)."""
        for attempt in range(1, self._max_attempts + 1):
            if stop.is_set():
                return None
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.settimeout(0.2)
                s.connect(self.cfg.monitor_sock_path)
                self.log.info(
                    "connected to cilium monitor %s",
                    self.cfg.monitor_sock_path,
                )
                return s
            except OSError as e:
                self.log.warning(
                    "monitor connect attempt %d/%d failed: %s",
                    attempt, self._max_attempts, e,
                )
                if attempt < self._max_attempts:
                    stop.wait(self._retry_delay)
        self.log.error(
            "failed to connect to cilium monitor after %d attempts",
            self._max_attempts,
        )
        return None

    def _flush(self, batch: list) -> None:
        rec, dns_names = events_to_records(batch)
        if dns_names:
            from retina_tpu.plugins.framing import publish_dns_names

            publish_dns_names(dns_names)
        if len(rec):
            self.emit(rec)
        batch.clear()

    def _consume_payload(self, pl: object, batch: list) -> None:
        if not isinstance(pl, dict):
            self.count_lost("parser", 1)
            return
        ptype = pl.get("Type", 0)
        if ptype == PAYLOAD_RECORD_LOST:
            # The dataplane itself dropped perf records before the
            # socket — surface it like the reference does (:171-173).
            self.count_lost("kernel", int(pl.get("Lost", 0)) or 1)
            return
        if ptype != PAYLOAD_EVENT_SAMPLE:
            self.count_lost("parser", 1)
            return
        ev = parse_perf_sample(bytes(pl.get("Data", b"")))
        if ev is None:
            # Debug/agent/L7 message types carry no packet; not a loss.
            return
        batch.append(ev)

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            conn = self._connect(stop)
            if conn is None:
                return
            try:
                self._monitor_loop(conn, stop)
            finally:
                conn.close()
            # EOF/decode failure: reconnect from scratch (reference
            # Start loop re-dials after monitorLoop returns, :96-106).

    def _monitor_loop(
        self, conn: socket.socket, stop: threading.Event
    ) -> None:
        dec = GobStreamDecoder()
        batch: list = []
        last_flush = time.monotonic()
        try:
            while not stop.is_set():
                try:
                    data = conn.recv(1 << 16)
                    if not data:
                        self.log.info("monitor socket EOF")
                        return
                except (TimeoutError, socket.timeout):
                    data = b""
                except OSError as e:
                    self.log.warning("monitor socket error: %s", e)
                    return
                if data:
                    try:
                        for pl in dec.feed(data):
                            self._consume_payload(pl, batch)
                    except GobError as e:
                        # Un-resynchronizable: gob framing is stateful,
                        # so drop the connection and re-dial (the
                        # reference counts and continues only for
                        # per-payload decode errors; a framing error
                        # likewise breaks its stream).
                        self.log.warning("gob stream error: %s", e)
                        self.count_lost("parser", 1)
                        return
                now = time.monotonic()
                if len(batch) >= BATCH_FRAMES or (
                    batch and now - last_flush >= BATCH_INTERVAL_S
                ):
                    self._flush(batch)
                    last_flush = now
        finally:
            # Every exit path (EOF, socket error, gob desync, stop)
            # flushes events already parsed — they are intact, and
            # dropping them silently would violate the drop-and-count
            # rule without even the count.
            if batch:
                self._flush(batch)

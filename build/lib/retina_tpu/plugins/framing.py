"""Shared record-frame wire format.

One framing for every socket-based record producer/consumer in the tree
(externalevents server, pktmon client): little-endian u32 length prefix,
then a msgpack doc ``{"records": <bytes of (N,16) uint32 le>,
"dns_names": {hash: name}}``. Extracted so the two consumers cannot
drift (endianness, caps, dns_names handling).
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable

import msgpack
import numpy as np

from retina_tpu.events.schema import NUM_FIELDS

MAX_FRAME = 64 << 20


def send_frame(sock: socket.socket, records: np.ndarray,
               dns_names: dict[int, str] | None = None) -> None:
    """Producer-side helper: ship a record block."""
    payload = msgpack.packb(
        {
            "records": np.ascontiguousarray(records, np.uint32).tobytes(),
            "dns_names": dns_names or {},
        }
    )
    sock.sendall(struct.pack("<I", len(payload)) + payload)


def decode_record_frame(frame: bytes) -> tuple[np.ndarray, dict[int, str]]:
    """Frame payload → ((N, 16) uint32 records, dns_names). Raises on a
    malformed frame; callers count the loss."""
    doc = msgpack.unpackb(frame, strict_map_key=False)
    rec = np.frombuffer(doc["records"], np.uint32).reshape(
        -1, NUM_FIELDS).copy()
    return rec, dict(doc.get("dns_names") or {})


def read_frames(
    conn: socket.socket,
    stop: threading.Event,
    on_frame: Callable[[bytes], None],
    log,
) -> None:
    """Drain frames from a connected socket until EOF, error, stop, or an
    oversized frame (which poisons the length stream — the connection is
    abandoned, as the reference drops a desynced monitor socket)."""
    buf = b""
    while not stop.is_set():
        try:
            chunk = conn.recv(1 << 20)
        except (TimeoutError, socket.timeout):
            continue
        except OSError:
            return
        if not chunk:
            return
        buf += chunk
        while len(buf) >= 4:
            (n,) = struct.unpack_from("<I", buf)
            if n > MAX_FRAME:
                log.error("frame too large (%d bytes); dropping conn", n)
                return
            if len(buf) < 4 + n:
                break
            frame, buf = buf[4:4 + n], buf[4 + n:]
            on_frame(frame)


def publish_dns_names(names: dict[int, str]) -> None:
    """Feed decoded qname strings to the DNS plugin's string table."""
    if not names:
        return
    from retina_tpu.plugins.dns import TOPIC_DNS_NAMES
    from retina_tpu.pubsub import get_pubsub

    get_pubsub().publish(TOPIC_DNS_NAMES, dict(names))

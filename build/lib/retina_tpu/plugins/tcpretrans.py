"""tcpretrans: TCP retransmission accounting.

Reference analog: pkg/plugin/tcpretrans — the Inspektor-Gadget tcpretrans
eBPF tracer emits per-socket retransmit flows (tcpretrans_linux.go). Host
analog: node-level RetransSegs deltas from /proc/net/snmp publish the
basic series, and EV_TCP_RETRANS events from packet sources ride the
device pipeline for the per-pod advanced series (pod_retrans rectangle).
"""

from __future__ import annotations

import threading

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin
from retina_tpu.sources import procfs


@registry.register
class TcpRetransPlugin(Plugin):
    name = "tcpretrans"

    def __init__(self, cfg: Config):
        super().__init__(cfg)
        self.proc_root = "/proc"
        self._base: int | None = None

    def _read(self) -> int:
        return procfs.read_snmp(self.proc_root).get("Tcp", {}).get(
            "RetransSegs", 0
        )

    def init(self) -> None:
        self._base = self._read()

    def read_and_publish(self) -> None:
        cur = self._read()
        base = self._base if self._base is not None else cur
        get_metrics().tcp_connection_stats.labels(
            statistic_name="RetransSegs"
        ).set(max(cur - base, 0))

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.read_and_publish()
            except Exception:
                self.log.exception("tcpretrans read failed")
            stop.wait(self.cfg.metrics_interval_s)

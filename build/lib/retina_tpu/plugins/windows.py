"""Windows plugins: hnsstats (HNS/VFP port counters) and pktmon.

Reference analogs:
- pkg/plugin/hnsstats/hnsstats_windows.go:97-226 — every metrics
  interval: list healthy HNS endpoints, read per-endpoint HNS counters,
  map endpoint MAC → VFP switch-port GUID (vfpctrl /list-vmswitch-port),
  read + parse ``vfpctrl /port <guid> /get-port-counter`` text, then set
  forward/drop/tcp-connection/tcp-flag gauges.
- pkg/plugin/hnsstats/vfp_counters_windows.go:63-200 — the text parsers
  mirrored here as pure functions (:func:`parse_vfp_port_counters`,
  :func:`parse_vmswitch_ports`).
- pkg/plugin/pktmon/pktmon_windows.go:107-180 — spawns a pktmon stream
  server subprocess and consumes its flow stream, feeding the metrics/
  hubble paths.

Design: the OS edge (running ``vfpctrl``/HNS queries, the pktmon server
binary) sits behind small injectable seams (:class:`HnsSource`, the
pktmon ``command``), so the collector/parser/aggregation logic — the
actual substance of both plugins — is cross-platform and fully tested on
Linux; only the default sources are win32-gated, matching the
reference's ``_windows.go`` build tags.

The pktmon wire format diverges deliberately: the reference serves
Cilium Observer gRPC over a named socket; here the subprocess streams
the framework's native length-prefixed msgpack record frames (the
externalevents framing, plugins/externalevents.py) — same process
topology, one fewer protocol in the tree.
"""

from __future__ import annotations

import shlex
import socket
import subprocess
import sys
import threading
import time
from typing import Optional, Protocol

from retina_tpu.config import Config
from retina_tpu.metrics import get_metrics
from retina_tpu.plugins import registry
from retina_tpu.plugins.api import Plugin, UnsupportedPlatform
from retina_tpu.plugins.framing import (
    decode_record_frame,
    publish_dns_names,
    read_frames,
)

INGRESS = "ingress"
EGRESS = "egress"
# Drop-reason labels (reference utils.Endpoint / utils.AclRule).
REASON_ENDPOINT = "endpoint"
REASON_ACL_RULE = "acl_rule"

# vfpctrl identifiers → (group, stat name); mirrors attachVfpCounter
# (vfp_counters_windows.go:63-110).
_VFP_IDENTIFIERS = {
    "SYNpackets": ("flags", "SYN"),
    "SYN-ACKpackets": ("flags", "SYNACK"),
    "FINpackets": ("flags", "FIN"),
    "RSTpackets": ("flags", "RST"),
    "TCPConnectionsVerified": ("conn", "Verified"),
    "TCPConnectionsTimedOut": ("conn", "TimedOutCount"),
    "TCPConnectionsReset": ("conn", "ResetCount"),
    "TCPConnectionsResetbySYN": ("conn", "ResetSyn"),
    "TCPConnectionsClosedbyFIN": ("conn", "ClosedFin"),
    "TCPHalfOpenTimeouts": ("conn", "TcpHalfOpenTimeouts"),
    "TCPConnectionsExpiredtoTimeWait": ("conn", "TimeWaitExpiredCount"),
    "DroppedACLpackets": ("drop", "acl"),
}


def parse_vfp_port_counters(raw: str) -> dict:
    """``vfpctrl /port <guid> /get-port-counter`` text → nested counters.

    Returns ``{"out": {...}, "in": {...}}`` with per-direction ``flags``,
    ``conn`` and ``drop`` groups. Mirrors parseVfpPortCounters
    (vfp_counters_windows.go:112-148): spaces stripped, the OUT block
    precedes the ``Direction-IN`` marker, lines are ``Identifier:Value``.
    """
    out: dict = {"out": {"flags": {}, "conn": {}, "drop": {}},
                 "in": {"flags": {}, "conn": {}, "drop": {}}}
    raw = raw.replace(" ", "")
    for direction, block in enumerate(raw.split("Direction-IN")):
        key = "out" if direction == 0 else "in"
        for line in block.replace("\r\n", "\n").split("\n"):
            fields = line.split(":")
            if len(fields) != 2:
                continue
            ident, value = fields
            if ident not in _VFP_IDENTIFIERS:
                continue
            try:
                count = int(value)
            except ValueError:
                continue
            group, stat = _VFP_IDENTIFIERS[ident]
            out[key][group][stat] = count
    return out


def parse_vmswitch_ports(raw: str) -> dict[str, str]:
    """``vfpctrl /list-vmswitch-port`` text → {MAC: port GUID}.

    Mirrors getMacToPortGuidMap (vfp_counters_windows.go:174-200):
    blank-line-separated port blocks with ``Portname:`` / ``MACaddress:``
    fields, spaces stripped.
    """
    kv: dict[str, str] = {}
    raw = raw.replace(" ", "").replace("\r\n", "\n")
    for block in raw.split("\n\n"):
        if "Portname" not in block or "MACaddress" not in block:
            continue
        port_name = mac = ""
        for line in block.split("\n"):
            key, sep, value = line.partition(":")
            if not sep:
                continue
            # HNS MACs are dash-separated so the reference's split-on-":"
            # works; taking the full remainder also tolerates colons.
            if key == "Portname":
                port_name = value
            elif key == "MACaddress":
                mac = value
        if port_name and mac:
            kv[mac] = port_name
    return kv


class HnsSource(Protocol):
    """The OS seam: what hnsstats reads from Windows."""

    def list_endpoints(self) -> list[dict]:
        """Healthy (attached-sharing) endpoints:
        [{"id", "mac", "ip"}] (hcn.ListEndpointsQuery analog)."""
        ...

    def endpoint_stats(self, endpoint_id: str) -> dict:
        """HNS per-endpoint counters (hcsshim.GetHNSEndpointStats):
        packets_received/packets_sent/bytes_received/bytes_sent/
        dropped_packets_incoming/dropped_packets_outgoing."""
        ...

    def vmswitch_ports_raw(self) -> str:
        """Raw ``vfpctrl /list-vmswitch-port`` output."""
        ...

    def port_counters_raw(self, port_guid: str) -> str:
        """Raw ``vfpctrl /port <guid> /get-port-counter`` output."""
        ...


class CommandHnsSource:
    """The real thing: shells out to hnsdiag/vfpctrl (win32 only)."""

    def _run(self, cmd: str) -> str:
        res = subprocess.run(
            ["cmd", "/c", cmd], capture_output=True, text=True, timeout=30,
        )
        if res.returncode != 0:
            # Surface the failure (access denied, VFP not loaded) instead
            # of publishing all-zero gauges from empty output.
            raise RuntimeError(
                f"{cmd.split()[0]} failed rc={res.returncode}: "
                f"{(res.stderr or res.stdout).strip()[:200]}"
            )
        return res.stdout

    def list_endpoints(self) -> list[dict]:
        import json as _json

        docs = _json.loads(self._run("hnsdiag list endpoints -df") or "[]")
        if isinstance(docs, dict):
            docs = list(docs.values())
        out = []
        for d in docs:
            ip = (d.get("IpConfigurations") or [{}])[0].get("IpAddress", "") \
                or d.get("IPAddress", "")
            if not ip:
                continue
            out.append({"id": d.get("ID", d.get("Id", "")),
                        "mac": d.get("MacAddress", ""), "ip": ip})
        return out

    def endpoint_stats(self, endpoint_id: str) -> dict:
        import json as _json

        doc = _json.loads(
            self._run(f"hnsdiag stats endpoint {endpoint_id} -df") or "{}")
        return {
            "packets_received": doc.get("PacketsReceived", 0),
            "packets_sent": doc.get("PacketsSent", 0),
            "bytes_received": doc.get("BytesReceived", 0),
            "bytes_sent": doc.get("BytesSent", 0),
            "dropped_packets_incoming": doc.get("DroppedPacketsIncoming", 0),
            "dropped_packets_outgoing": doc.get("DroppedPacketsOutgoing", 0),
        }

    def vmswitch_ports_raw(self) -> str:
        return self._run("vfpctrl /list-vmswitch-port")

    def port_counters_raw(self, port_guid: str) -> str:
        return self._run(f"vfpctrl /port {port_guid} /get-port-counter")


class HnsStatsPlugin(Plugin):
    """Interval-pull collector over an :class:`HnsSource`."""

    name = "hnsstats"

    def __init__(self, cfg: Config, source: Optional[HnsSource] = None):
        super().__init__(cfg)
        self.source = source

    def init(self) -> None:
        if self.source is None:
            if sys.platform != "win32":
                raise UnsupportedPlatform("hnsstats requires Windows HNS")
            self.source = CommandHnsSource()

    def pull_once(self) -> int:
        """One collection pass (pullHnsStats body,
        hnsstats_windows.go:97-160). Returns endpoints observed."""
        m = get_metrics()
        endpoints = self.source.list_endpoints()
        mac_ports = parse_vmswitch_ports(self.source.vmswitch_ports_raw())
        # Node totals: HNS counters are per-endpoint; the node gauges sum
        # them (pod attribution belongs to the enrichment path).
        tot = {k: 0 for k in ("rx_pkts", "tx_pkts", "rx_bytes", "tx_bytes",
                              "drop_in", "drop_out")}
        vfp_in: dict = {"flags": {}, "conn": {}, "drop": {}}
        vfp_out: dict = {"flags": {}, "conn": {}, "drop": {}}
        for ep in endpoints:
            if not ep.get("ip"):
                continue
            try:
                st = self.source.endpoint_stats(ep["id"])
            except Exception:  # noqa: BLE001 — endpoint may be mid-teardown
                self.log.exception("endpoint stats failed: %s", ep["id"])
                continue
            tot["rx_pkts"] += st.get("packets_received", 0)
            tot["tx_pkts"] += st.get("packets_sent", 0)
            tot["rx_bytes"] += st.get("bytes_received", 0)
            tot["tx_bytes"] += st.get("bytes_sent", 0)
            tot["drop_in"] += st.get("dropped_packets_incoming", 0)
            tot["drop_out"] += st.get("dropped_packets_outgoing", 0)
            guid = mac_ports.get(ep.get("mac", ""))
            if not guid:
                self.log.warning("no VFP port for mac %s", ep.get("mac"))
                continue
            try:
                vfp = parse_vfp_port_counters(
                    self.source.port_counters_raw(guid))
            except Exception:  # noqa: BLE001
                self.log.exception("VFP counters failed: %s", guid)
                continue
            for agg, side in ((vfp_in, "in"), (vfp_out, "out")):
                for grp in ("flags", "conn", "drop"):
                    for k, v in vfp[side].get(grp, {}).items():
                        agg[grp][k] = agg[grp].get(k, 0) + v

        # notifyHnsStats (hnsstats_windows.go:163-216), same families.
        m.forward_count.labels(direction=INGRESS).set(tot["rx_pkts"])
        m.forward_count.labels(direction=EGRESS).set(tot["tx_pkts"])
        m.forward_bytes.labels(direction=INGRESS).set(tot["rx_bytes"])
        m.forward_bytes.labels(direction=EGRESS).set(tot["tx_bytes"])
        m.drop_count.labels(reason=REASON_ENDPOINT,
                            direction=INGRESS).set(tot["drop_in"])
        m.drop_count.labels(reason=REASON_ENDPOINT,
                            direction=EGRESS).set(tot["drop_out"])
        if "acl" in vfp_in["drop"]:
            m.drop_count.labels(reason=REASON_ACL_RULE,
                                direction=INGRESS).set(vfp_in["drop"]["acl"])
        if "acl" in vfp_out["drop"]:
            m.drop_count.labels(reason=REASON_ACL_RULE,
                                direction=EGRESS).set(vfp_out["drop"]["acl"])
        # Connection stats come from the IN direction, TCP flags from
        # both, exactly as notifyHnsStats reads them.
        for stat, v in vfp_in["conn"].items():
            m.tcp_connection_stats.labels(statistic_name=stat).set(v)
        for flag, v in vfp_in["flags"].items():
            m.tcp_flag_counters.labels(flag=flag).set(v)
        return len(endpoints)

    def start(self, stop: threading.Event) -> None:
        while not stop.is_set():
            try:
                self.pull_once()
            except Exception:  # noqa: BLE001
                self.log.exception("hnsstats pull failed")
            stop.wait(self.cfg.metrics_interval_s)


# ---------------------------------------------------------------------
class PktmonPlugin(Plugin):
    """Supervise a pktmon stream-server subprocess and consume its
    record frames into the event sink.

    Process topology mirrors the reference (RunPktMonServer + the
    GetFlows loop, pktmon_windows.go:107-180): the server binary owns
    the ETW session; this plugin restarts it on exit with backoff and
    never lets a stream failure kill the agent. The subprocess LISTENS
    on a unix socket and streams length-prefixed msgpack frames of
    (N, 16) uint32 records; we connect as the client.
    """

    name = "pktmon"

    def __init__(self, cfg: Config, command: str = "",
                 socket_path: str = ""):
        super().__init__(cfg)
        self.socket_path = (socket_path or cfg.pktmon_socket
                            or "/temp/retina-pktmon.sock")
        self.command = command or cfg.pktmon_command
        self._proc: Optional[subprocess.Popen] = None

    def init(self) -> None:
        if not self.command:
            if sys.platform != "win32":
                raise UnsupportedPlatform("pktmon requires Windows")
            self.command = (
                f"controller-pktmon.exe --socketpath {self.socket_path}"
            )

    # -- subprocess supervision ---------------------------------------
    def _spawn(self) -> None:
        self._proc = subprocess.Popen(
            shlex.split(self.command),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        self.log.info("pktmon server started (pid %d)", self._proc.pid)

    def _connect(self, stop: threading.Event) -> Optional[socket.socket]:
        deadline = time.monotonic() + 10
        while not stop.is_set() and time.monotonic() < deadline:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.settimeout(1.0)
                s.connect(self.socket_path)
                return s
            except OSError:
                s.close()
                time.sleep(0.2)
        return None

    def _consume(self, conn: socket.socket, stop: threading.Event) -> None:
        """Drain frames → sink + external channel (the GetFlow loop);
        same framing as externalevents (plugins/framing.py)."""
        read_frames(conn, stop, self._handle_frame, self.log)

    def _handle_frame(self, frame: bytes) -> None:
        try:
            rec, names = decode_record_frame(frame)
        except Exception:  # noqa: BLE001
            self.count_lost("decode", 1)
            self.log.exception("bad pktmon frame")
            return
        publish_dns_names(names)
        self.emit(rec)

    def start(self, stop: threading.Event) -> None:
        backoff = 1.0
        while not stop.is_set():
            try:
                self._spawn()
            except Exception:  # noqa: BLE001
                self.log.exception("pktmon server spawn failed")
                stop.wait(min(backoff, 30.0))
                backoff = min(backoff * 2, 30.0)
                continue
            conn = self._connect(stop)
            if conn is not None:
                backoff = 1.0
                try:
                    self._consume(conn, stop)
                finally:
                    conn.close()
            if self._proc is not None:
                self._proc.terminate()
                try:
                    self._proc.wait(5)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
            if not stop.is_set():
                self.log.warning("pktmon stream ended; restarting in %.0fs",
                                 min(backoff, 30.0))
                stop.wait(min(backoff, 30.0))
                backoff = min(backoff * 2, 30.0)

    def stop(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()


registry.add(HnsStatsPlugin.name, HnsStatsPlugin)
registry.add(PktmonPlugin.name, PktmonPlugin)

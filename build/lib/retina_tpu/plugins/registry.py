"""Plugin registry: name → constructor.

Reference analog: pkg/plugin/registry/registry.go:36-53 — a package-level
map populated by plugin ``init()`` self-registration, panicking on
duplicates. Same contract: :func:`add` raises on dup, :func:`get` raises
KeyError on unknown names (pluginmanager surfaces both as fatal).
"""

from __future__ import annotations

from typing import Callable, Type

from retina_tpu.config import Config
from retina_tpu.plugins import api  # noqa: F401 — quoted annotations below

PluginCtor = Callable[[Config], "api.Plugin"]

_registry: dict[str, PluginCtor] = {}


def add(name: str, ctor: PluginCtor) -> None:
    if name in _registry:
        raise ValueError(f"plugin {name!r} already registered")
    _registry[name] = ctor


def get(name: str) -> PluginCtor:
    if name not in _registry:
        raise KeyError(
            f"plugin {name!r} not registered (known: {sorted(_registry)})"
        )
    return _registry[name]


def names() -> list[str]:
    return sorted(_registry)


def register(cls: Type["api.Plugin"]) -> Type["api.Plugin"]:
    """Class decorator: the init()+Add self-registration idiom."""
    add(cls.name, cls)
    return cls

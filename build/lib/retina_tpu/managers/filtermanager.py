"""FilterManager: refcounted IPs-of-interest façade.

Reference analog: pkg/managers/filtermanager — a singleton façade over the
BPF LPM filter map with a refcounting cache keyed by (IP, requestor,
ruleID) and exponential-backoff retry on map writes
(manager_linux.go:31-100). Here the "map" is the engine's device-side
filter IdentityMap (pipeline masks events whose endpoints match neither a
pod identity nor this set — models/pipeline.py filter block); writes are
debounced rebuilds of that table.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Callable, Optional

from retina_tpu.common import retry
from retina_tpu.log import logger


class FilterManager:
    def __init__(
        self,
        apply_fn: Optional[Callable[[set[int]], None]] = None,
        max_retries: int = 5,
    ):
        """``apply_fn`` receives the full IP set on every change —
        typically ``engine.update_filter_ips``."""
        self._log = logger("filtermanager")
        self._lock = threading.Lock()
        # ip -> {(requestor, rule_id)}
        self._refs: dict[int, set[tuple[str, str]]] = {}
        self._apply = apply_fn
        self._retries = max_retries
        self._deferring = 0
        self._dirty = False

    def _push(self) -> None:
        if self._apply is None:
            return
        with self._lock:
            ips = set(self._refs)
        # Retry covers TRANSIENT device-write failures only; overflow is
        # handled inside the engine (clamp + lost_table_entries counter,
        # engine.update_filter_ips) because backoff can't fix a
        # deterministic condition. A final failure is logged, never
        # raised into the pubsub callback that triggered the push — the
        # reference likewise counts failures and stays up
        # (manager_linux.go:62-100).
        try:
            retry(lambda: self._apply(ips), attempts=self._retries,
                  base_delay_s=0.05)
        except Exception:
            from retina_tpu.metrics import get_metrics

            get_metrics().filter_push_failures.inc()
            self._log.exception(
                "filter push failed after %d attempts (%d IPs)",
                self._retries, len(ips),
            )

    def _maybe_push(self) -> None:
        with self._lock:
            if self._deferring:
                self._dirty = True
                return
        self._push()

    @contextlib.contextmanager
    def deferred_push(self):
        """Batch many add/delete calls into ONE table push — e.g. a
        namespace annotation toggle resyncing every pod in it."""
        with self._lock:
            self._deferring += 1
        try:
            yield
        finally:
            with self._lock:
                self._deferring -= 1
                do = self._deferring == 0 and self._dirty
                if do:
                    self._dirty = False
            if do:
                self._push()

    def add_ips(self, ips: list[int], requestor: str, rule_id: str) -> None:
        """Refcounted add (manager_linux.go AddIPs :62-100)."""
        changed = False
        with self._lock:
            for ip in ips:
                refs = self._refs.setdefault(ip, set())
                if not refs:
                    changed = True
                refs.add((requestor, rule_id))
        if changed:
            self._maybe_push()

    def delete_ips(self, ips: list[int], requestor: str, rule_id: str) -> None:
        """Deletes only when the last (requestor, rule) drops its ref."""
        changed = False
        with self._lock:
            for ip in ips:
                refs = self._refs.get(ip)
                if refs is None:
                    continue
                refs.discard((requestor, rule_id))
                if not refs:
                    del self._refs[ip]
                    changed = True
        if changed:
            self._maybe_push()

    def has_ip(self, ip: int) -> bool:
        with self._lock:
            return ip in self._refs

    def ip_count(self) -> int:
        with self._lock:
            return len(self._refs)

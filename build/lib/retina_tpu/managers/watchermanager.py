"""WatcherManager: runs watchers on a refresh ticker.

Reference analog: pkg/managers/watchermanager — starts each watcher and
calls Refresh on a 30s ticker (watchermanager.go:18-19,66-76).
"""

from __future__ import annotations

import threading

from retina_tpu.log import logger

REFRESH_INTERVAL_S = 30.0


class WatcherManager:
    def __init__(self, watchers: list, interval_s: float = REFRESH_INTERVAL_S):
        self._log = logger("watchermanager")
        self._watchers = watchers
        self._interval = interval_s
        self._thread: threading.Thread | None = None

    def refresh_all(self) -> None:
        for w in self._watchers:
            try:
                w.refresh()
            except Exception:
                self._log.exception(
                    "watcher %s refresh failed", getattr(w, "name", w)
                )

    def start(self, stop: threading.Event) -> None:
        self.refresh_all()  # initial snapshot immediately

        def loop() -> None:
            while not stop.wait(self._interval):
                self.refresh_all()

        self._thread = threading.Thread(
            target=loop, name="watchermanager", daemon=True
        )
        self._thread.start()

"""Management layer (reference pkg/managers, SURVEY.md §2.4)."""

"""In-process publish/subscribe bus.

Reference analog: pkg/pubsub/pubsub.go — a topic → callback registry where
``Publish`` fires every callback in its own goroutine (pubsub.go:40-59),
``Subscribe`` returns a UUID used by ``Unsubscribe`` (:62-113). This is the
bus the north star extends to carry control-plane ↔ TPU-worker traffic
(BASELINE.json), so it is the seam between the Go-shaped control plane and
the JAX feed loop here too.

Concurrency: callbacks run on a shared thread pool (goroutine analog);
callback exceptions are logged, never propagated to the publisher — a
misbehaving subscriber must not take down the data plane.
"""

from __future__ import annotations

import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable

from retina_tpu.log import logger

CallBackFunc = Callable[[Any], None]


class PubSub:
    """Thread-safe topic bus (reference PubSubInterface)."""

    def __init__(self, max_workers: int = 8):
        self._lock = threading.RLock()
        self._topics: dict[str, dict[str, CallBackFunc]] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="pubsub"
        )
        self._log = logger("pubsub")

    def publish(self, topic: str, msg: Any) -> None:
        """Fire-and-forget to every subscriber (pubsub.go:40-59)."""
        with self._lock:
            subs = list(self._topics.get(topic, {}).values())
        for cb in subs:
            self._pool.submit(self._safe_call, cb, msg, topic)

    def publish_sync(self, topic: str, msg: Any) -> None:
        """Synchronous variant: callbacks run inline, still error-isolated.
        Used on paths that need ordering (e.g. cache event fan-out in
        tests)."""
        with self._lock:
            subs = list(self._topics.get(topic, {}).values())
        for cb in subs:
            self._safe_call(cb, msg, topic)

    def _safe_call(self, cb: CallBackFunc, msg: Any, topic: str) -> None:
        try:
            cb(msg)
        except Exception:
            self._log.exception("subscriber callback failed topic=%s", topic)

    def subscribe(self, topic: str, cb: CallBackFunc) -> str:
        """Register; returns the unsubscribe UUID (pubsub.go:62-80)."""
        sub_id = str(uuid.uuid4())
        with self._lock:
            self._topics.setdefault(topic, {})[sub_id] = cb
        return sub_id

    def unsubscribe(self, topic: str, sub_id: str) -> None:
        with self._lock:
            subs = self._topics.get(topic)
            if not subs or sub_id not in subs:
                raise KeyError(f"no subscriber {sub_id} on topic {topic}")
            del subs[sub_id]

    def has_subscribers(self, topic: str) -> bool:
        with self._lock:
            return bool(self._topics.get(topic))

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)


_singleton: PubSub | None = None
_singleton_lock = threading.Lock()


def get_pubsub() -> PubSub:
    """Process-wide bus (reference sync.Once singleton pattern)."""
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = PubSub()
        return _singleton

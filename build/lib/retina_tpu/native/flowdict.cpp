// Persistent flow-descriptor dictionary: descriptor -> stable slot id.
//
// The C++ twin of retina_tpu/parallel/flowdict.py (see that module for
// the wire-v2 contract and the kernel-map analogy). The Python dict
// version costs a per-row interpreter loop under the GIL (~100-300 ms
// per 150k-row production quantum on a 1-core agent box — a serial tax
// on the feed path); this version is one GIL-released pass over an open
// addressing table of resident descriptors.
//
// Must stay semantically identical to HostFlowDict — the test suite
// cross-checks the two on random batches:
// - ids are assigned in row order starting at 1 (0 = overflow sentinel);
// - a batch that would overflow capacity clears the table first
//   (generation bump) IF clearing lets it fit; descriptors beyond
//   capacity get sentinel id 0 with is_new=1;
// - repeats within a batch resolve to the id just assigned.

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace {

constexpr int NUM_FIELDS = 16;
// Descriptor columns (combine.py KEY_COLS order is irrelevant here as
// long as hashing/compare agree internally — but keep the combiner's
// set: everything except TS_LO/TS_HI/BYTES/PACKETS).
constexpr int KEY_COLS[12] = {2, 3, 4, 5, 8, 9, 10, 11, 12, 13, 14, 15};
constexpr int KEY_LEN = 12;

// The extracted key is contiguous: hash it as six u64 words (half the
// mix rounds of the per-column loop; this probe sits on the per-quantum
// feed path).
inline uint64_t hash_desc(const uint32_t* key) {
  uint64_t h = 0x9E3779B97F4A7C15ull, v;
  for (int i = 0; i < KEY_LEN / 2; i++) {
    memcpy(&v, key + 2 * i, 8);
    h ^= v;
    h *= 0xFF51AFD7ED558CCDull;
    h ^= h >> 33;
  }
  return h;
}

struct Slot {
  uint32_t key[KEY_LEN];
  uint32_t id;  // 0 = empty
};

struct FlowDict {
  Slot* slots;
  size_t n_slots;  // power of two >= 2*capacity
  size_t mask;
  uint32_t capacity;  // max assignable id is capacity-1
  uint32_t count;     // descriptors resident
  uint32_t generation;
};

inline void extract_key(const uint32_t* row, uint32_t* key) {
  for (int i = 0; i < KEY_LEN; i++) key[i] = row[KEY_COLS[i]];
}

inline bool key_eq(const uint32_t* a, const uint32_t* b) {
  return memcmp(a, b, KEY_LEN * sizeof(uint32_t)) == 0;
}

// Find the slot holding `key`, or the empty slot where it belongs.
inline Slot* probe(FlowDict* d, const uint32_t* key, uint64_t h) {
  size_t s = h & d->mask;
  for (;;) {
    Slot* sl = &d->slots[s];
    if (sl->id == 0 || key_eq(sl->key, key)) return sl;
    s = (s + 1) & d->mask;
  }
}

}  // namespace

extern "C" {

void* rt_flowdict_new(uint32_t capacity) {
  FlowDict* d = (FlowDict*)malloc(sizeof(FlowDict));
  if (!d) return nullptr;
  size_t slots = 16;
  while (slots < 2 * (size_t)capacity) slots <<= 1;
  d->slots = (Slot*)calloc(slots, sizeof(Slot));
  if (!d->slots) {
    free(d);
    return nullptr;
  }
  d->n_slots = slots;
  d->mask = slots - 1;
  d->capacity = capacity;
  d->count = 0;
  d->generation = 0;
  return d;
}

void rt_flowdict_free(void* h) {
  if (!h) return;
  FlowDict* d = (FlowDict*)h;
  free(d->slots);
  free(d);
}

void rt_flowdict_clear(void* h) {
  FlowDict* d = (FlowDict*)h;
  memset(d->slots, 0, d->n_slots * sizeof(Slot));
  d->count = 0;
  d->generation++;
}

uint32_t rt_flowdict_len(void* h) { return ((FlowDict*)h)->count; }

uint32_t rt_flowdict_generation(void* h) {
  return ((FlowDict*)h)->generation;
}

// rows: (n, 16) u32 row-major. ids: out (n,) u32. is_new: out (n,) u8.
// Returns the generation AFTER the call (a bump means the table
// cleared before assignment).
uint32_t rt_flowdict_assign(void* h, const uint32_t* rows, size_t n,
                            uint32_t* ids, uint8_t* is_new) {
  FlowDict* d = (FlowDict*)h;
  // Overflow pre-check (HostFlowDict contract): clearing mid-batch
  // would hand out known-ids the new generation never assigned.
  if ((size_t)d->count + n > d->capacity) {
    size_t fresh = 0;
    uint32_t key[KEY_LEN];
    // Count batch-distinct unseen descriptors with a throwaway pass:
    // mark seen-in-batch by probing the main table WITHOUT inserting,
    // plus a scratch table for intra-batch repeats.
    size_t sslots = 16;
    while (sslots < 2 * n) sslots <<= 1;
    Slot* scratch = (Slot*)calloc(sslots, sizeof(Slot));
    if (scratch) {
      const size_t smask = sslots - 1;
      for (size_t i = 0; i < n; i++) {
        extract_key(rows + i * NUM_FIELDS, key);
        uint64_t hh = hash_desc(key);
        Slot* main = probe(d, key, hh);
        if (main->id != 0) continue;  // already resident
        size_t s = hh & smask;
        for (;;) {
          Slot* sl = &scratch[s];
          if (sl->id == 0) {
            memcpy(sl->key, key, sizeof(key));
            sl->id = 1;
            fresh++;
            break;
          }
          if (key_eq(sl->key, key)) break;
          s = (s + 1) & smask;
        }
      }
      free(scratch);
      if ((size_t)d->count + fresh > d->capacity) rt_flowdict_clear(h);
    } else {
      rt_flowdict_clear(h);  // allocation pressure: degrade safely
    }
  }
  uint32_t key[KEY_LEN];
  for (size_t i = 0; i < n; i++) {
    extract_key(rows + i * NUM_FIELDS, key);
    Slot* sl = probe(d, key, hash_desc(key));
    if (sl->id != 0) {
      ids[i] = sl->id;
      is_new[i] = 0;
      continue;
    }
    is_new[i] = 1;
    uint32_t next = d->count + 1;  // ids start at 1
    if (next < d->capacity) {
      memcpy(sl->key, key, sizeof(key));
      sl->id = next;
      d->count = next;
      ids[i] = next;
    } else {
      ids[i] = 0;  // overflow sentinel: ships as a table-less full row
    }
  }
  return d->generation;
}

}  // extern "C"

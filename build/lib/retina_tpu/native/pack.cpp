// Host-side wire packer: (n, 16) schema rows -> (n, 12) packed lanes.
//
// The C++ twin of retina_tpu/parallel/wire.py pack_records (see that
// module for the lane layout and saturation bounds). Packing runs on
// every flush quantum right before the host->device transfer, so its
// cost lands on the feed path's critical section; the numpy version
// spends ~19% of the host path in strided column copies + u64
// timestamp math, this single pass is memory-bound.
//
// Must stay semantically identical to pack_records' numpy math — the
// test suite cross-checks the two on random batches (including zero
// timestamps, values past every saturation bound, and ts < base
// wraparound).

#include <cstdint>
#include <cstring>

namespace {

constexpr int NUM_FIELDS = 16;
constexpr int PACKED_FIELDS = 12;
// Field indices (retina_tpu/events/schema.py).
constexpr int F_TS_LO = 0, F_TS_HI = 1, F_SRC_IP = 2, F_DST_IP = 3,
              F_PORTS = 4, F_META = 5, F_BYTES = 6, F_PACKETS = 7,
              F_VERDICT = 8, F_DROP_REASON = 9, F_TSVAL = 10,
              F_TSECR = 11, F_DNS = 12, F_DNS_QHASH = 13,
              F_EVENT_TYPE = 14, F_IFINDEX = 15;

inline uint32_t min_u32(uint32_t a, uint32_t b) { return a < b ? a : b; }

}  // namespace

extern "C" {

// Minimum nonzero 64-bit timestamp over rows (0 if none) — the TS_REL
// base shared by every wire array cut from one flush (wire.py
// batch_ts_base).
uint64_t rt_ts_base(const uint32_t* rows, size_t n) {
  uint64_t base = UINT64_MAX;
  for (size_t i = 0; i < n; i++) {
    const uint32_t* r = rows + i * NUM_FIELDS;
    uint64_t ts = ((uint64_t)r[F_TS_HI] << 32) | r[F_TS_LO];
    if (ts > 0 && ts < base) base = ts;
  }
  return base == UINT64_MAX ? 0 : base;
}

// rows: (n, 16) u32 row-major -> out: (n, 12) u32 row-major.
// Matches pack_records' numpy semantics exactly, including the
// unsigned wrap for ts < base (numpy u64 subtraction wraps, then the
// min() clamp saturates the relative timestamp).
void rt_pack(const uint32_t* rows, size_t n, uint64_t base,
             uint32_t* out) {
  constexpr uint64_t U32 = 0xFFFFFFFFull;
  for (size_t i = 0; i < n; i++) {
    const uint32_t* r = rows + i * NUM_FIELDS;
    uint32_t* o = out + i * PACKED_FIELDS;
    uint64_t ts = ((uint64_t)r[F_TS_HI] << 32) | r[F_TS_LO];
    uint64_t diff = ts - base;  // wraps when ts < base, like numpy u64
    o[0] = ts > 0 ? (uint32_t)((diff < U32 - 1 ? diff : U32 - 1) + 1)
                  : 0;
    o[1] = r[F_SRC_IP];
    o[2] = r[F_DST_IP];
    o[3] = r[F_PORTS];
    o[4] = r[F_META];
    o[5] = r[F_BYTES];
    o[6] = r[F_PACKETS];
    o[7] = (min_u32(r[F_VERDICT], 7) << 29)
         | (min_u32(r[F_DROP_REASON], 255) << 21)
         | (min_u32(r[F_EVENT_TYPE], 15) << 17)
         | min_u32(r[F_IFINDEX], 0x1FFFF);
    o[8] = r[F_TSVAL];
    o[9] = r[F_TSECR];
    o[10] = r[F_DNS];
    o[11] = r[F_DNS_QHASH];
  }
}

}  // extern "C"

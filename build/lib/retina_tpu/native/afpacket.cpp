// Live AF_PACKET capture over a TPACKET_V3 mmap'd ring.
//
// Reference analog: the packetparser's kernel->user perf ring
// (pkg/plugin/packetparser/types_linux.go:67-69 — 32 pages/CPU
// "determined via testing on a large cluster"; the kernel writes packet
// records, userspace drains blocks). A Python recv() per packet caps
// live capture around 50-100k pps on one core; TPACKET_V3 hands
// userspace whole BLOCKS of frames via shared memory with one poll()
// per block, and the frame decode runs in C (rt_decode_eth_frame,
// decoder.cpp) straight into the 16-lane record layout the device
// wants. Kernel-side drops stay visible through PACKET_STATISTICS —
// the same drop-and-count contract as everywhere else.
//
// Exposed via ctypes (native/__init__.py AfPacketRing); the plugin
// falls back to the per-packet Python socket loop when unavailable.

#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__linux__)

#include <arpa/inet.h>
#include <cerrno>
#include <linux/if_ether.h>
#include <linux/if_packet.h>
#include <net/if.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

extern "C" bool rt_decode_eth_frame(const uint8_t* pkt, size_t caplen,
                                    uint64_t ts_ns, uint32_t obs_point,
                                    uint32_t direction, uint32_t* r);

namespace {

constexpr int NUM_FIELDS = 16;

struct AfpHandle {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t map_len = 0;
  uint32_t block_size = 0;
  uint32_t block_nr = 0;
  uint32_t cur_block = 0;
  uint32_t resume_idx = 0;  // packets already consumed from cur_block
  uint64_t kernel_drops = 0;  // cumulative from PACKET_STATISTICS
};

}  // namespace

extern "C" {

// Open a TPACKET_V3 rx ring on `iface` ("" = all interfaces).
// Returns an opaque handle or nullptr (errno describes the failure —
// typically EPERM without CAP_NET_RAW).
void* rt_afp_open(const char* iface, uint32_t block_size,
                  uint32_t block_nr) {
  if (block_size == 0) block_size = 1u << 20;  // 1 MiB blocks
  if (block_nr == 0) block_nr = 32;            // 32 MiB ring
  // Protocol 0: the socket receives NOTHING until bind() attaches it to
  // the interface with ETH_P_ALL — otherwise frames from every
  // interface land in the ring during setup and get misattributed.
  int fd = socket(AF_PACKET, SOCK_RAW, 0);
  if (fd < 0) return nullptr;

  int ver = TPACKET_V3;
  if (setsockopt(fd, SOL_PACKET, PACKET_VERSION, &ver, sizeof(ver)) != 0) {
    close(fd);
    return nullptr;
  }
  struct tpacket_req3 req;
  std::memset(&req, 0, sizeof(req));
  req.tp_block_size = block_size;
  req.tp_block_nr = block_nr;
  req.tp_frame_size = 2048;  // v3 packs variably; sizing hint only
  req.tp_frame_nr = (block_size / req.tp_frame_size) * block_nr;
  req.tp_retire_blk_tov = 10;  // ms: hand over partial blocks promptly
  req.tp_feature_req_word = 0;
  if (setsockopt(fd, SOL_PACKET, PACKET_RX_RING, &req, sizeof(req)) != 0) {
    close(fd);
    return nullptr;
  }
  size_t map_len = static_cast<size_t>(block_size) * block_nr;
  void* map = mmap(nullptr, map_len, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_LOCKED, fd, 0);
  if (map == MAP_FAILED) {
    // MAP_LOCKED can exceed RLIMIT_MEMLOCK; retry unlocked.
    map = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  }
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  struct sockaddr_ll ll;
  std::memset(&ll, 0, sizeof(ll));
  ll.sll_family = AF_PACKET;
  ll.sll_protocol = htons(ETH_P_ALL);
  ll.sll_ifindex = (iface && iface[0]) ? static_cast<int>(
                       if_nametoindex(iface)) : 0;
  if (iface && iface[0] && ll.sll_ifindex == 0) {
    munmap(map, map_len);
    close(fd);
    return nullptr;
  }
  if (bind(fd, reinterpret_cast<struct sockaddr*>(&ll), sizeof(ll)) != 0) {
    munmap(map, map_len);
    close(fd);
    return nullptr;
  }
  AfpHandle* h = new AfpHandle();
  h->fd = fd;
  h->map = static_cast<uint8_t*>(map);
  h->map_len = map_len;
  h->block_size = block_size;
  h->block_nr = block_nr;
  return h;
}

// Drain ready blocks into out[max_records][16]. Waits up to timeout_ms
// for the first ready block. Returns records decoded (>= 0) or -1 on a
// poll error. n_seen counts every frame the kernel handed over
// (decoded or not); frames beyond max_records stay in the ring for the
// next call (the block is only released once fully consumed).
// DNS sidecar: raw frames of decoded DNS packets are appended to
// dns_buf as [u16 caplen][frame bytes] up to dns_cap (host Python
// extracts qname STRINGS from them — strings never cross into the
// record lanes). dns_buf may be null.
long rt_afp_poll(void* handle, uint32_t timeout_ms, uint32_t obs_point,
                 uint32_t* out, size_t max_records, uint64_t* n_seen,
                 uint8_t* dns_buf, size_t dns_cap, size_t* dns_used) {
  AfpHandle* h = static_cast<AfpHandle*>(handle);
  const uint32_t direction = (obs_point == 1 || obs_point == 2) ? 1u : 2u;
  size_t n = 0;
  if (n_seen) *n_seen = 0;
  if (dns_used) *dns_used = 0;
  bool waited = false;
  while (n < max_records) {
    uint8_t* block = h->map + static_cast<size_t>(h->cur_block) *
                                  h->block_size;
    auto* bd = reinterpret_cast<struct tpacket_block_desc*>(block);
    if (!(bd->hdr.bh1.block_status & TP_STATUS_USER)) {
      if (waited || n > 0) break;  // drained everything ready
      struct pollfd pfd = {h->fd, POLLIN | POLLERR, 0};
      int rc = poll(&pfd, 1, static_cast<int>(timeout_ms));
      if (rc < 0) {
        if (errno == EINTR) continue;  // signals are not errors
        return -1;
      }
      waited = true;
      if (rc == 0) break;
      continue;
    }
    uint32_t num_pkts = bd->hdr.bh1.num_pkts;
    auto* ppd = reinterpret_cast<struct tpacket3_hdr*>(
        block + bd->hdr.bh1.offset_to_first_pkt);
    bool partial = false;
    for (uint32_t i = 0; i < num_pkts; i++) {
      if (i >= h->resume_idx) {
        if (n >= max_records) {
          // Out buffer full mid-block: remember how far we got; the
          // next call resumes at this packet without re-emitting
          // earlier frames.
          h->resume_idx = i;
          partial = true;
          break;
        }
        if (n_seen) (*n_seen)++;
        const uint8_t* frame = reinterpret_cast<const uint8_t*>(ppd) +
                               ppd->tp_mac;
        uint64_t ts_ns = static_cast<uint64_t>(ppd->tp_sec) *
                             1000000000ull +
                         ppd->tp_nsec;
        uint32_t* r = out + n * NUM_FIELDS;
        if (rt_decode_eth_frame(frame, ppd->tp_snaplen, ts_ns, obs_point,
                                direction, r)) {
          // EVENT_TYPE lanes 2/3 = DNS req/resp (events/schema.py):
          // stash the raw frame for the host-side qname string pass.
          if (dns_buf && dns_used && (r[14] == 2u || r[14] == 3u) &&
              *dns_used + 2 + ppd->tp_snaplen <= dns_cap) {
            uint16_t cl = static_cast<uint16_t>(
                ppd->tp_snaplen > 0xFFFF ? 0xFFFF : ppd->tp_snaplen);
            std::memcpy(dns_buf + *dns_used, &cl, 2);
            std::memcpy(dns_buf + *dns_used + 2, frame, cl);
            *dns_used += 2 + cl;
          }
          n++;
        }
      }
      ppd = reinterpret_cast<struct tpacket3_hdr*>(
          reinterpret_cast<uint8_t*>(ppd) + ppd->tp_next_offset);
    }
    if (partial) break;
    h->resume_idx = 0;
    bd->hdr.bh1.block_status = TP_STATUS_KERNEL;
    __sync_synchronize();
    h->cur_block = (h->cur_block + 1) % h->block_nr;
  }
  return static_cast<long>(n);
}

// Cumulative kernel drop count (PACKET_STATISTICS is read-and-reset;
// the handle accumulates so callers see a monotonic counter).
uint64_t rt_afp_drops(void* handle) {
  AfpHandle* h = static_cast<AfpHandle*>(handle);
  struct tpacket_stats_v3 st;
  socklen_t len = sizeof(st);
  if (getsockopt(h->fd, SOL_PACKET, PACKET_STATISTICS, &st, &len) == 0) {
    h->kernel_drops += st.tp_drops;
  }
  return h->kernel_drops;
}

void rt_afp_close(void* handle) {
  AfpHandle* h = static_cast<AfpHandle*>(handle);
  if (h->map) munmap(h->map, h->map_len);
  if (h->fd >= 0) close(h->fd);
  delete h;
}

}  // extern "C"

#else  // !__linux__

extern "C" {
void* rt_afp_open(const char*, uint32_t, uint32_t) { return nullptr; }
long rt_afp_poll(void*, uint32_t, uint32_t, uint32_t*, size_t, uint64_t*,
                 uint8_t*, size_t, size_t*) {
  return -1;
}
uint64_t rt_afp_drops(void*) { return 0; }
void rt_afp_close(void*) {}
}

#endif

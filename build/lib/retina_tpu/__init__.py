"""retina_tpu — a TPU-native network observability framework.

A ground-up re-design of the capabilities of Retina (reference:
/root/reference, a Kubernetes network observability platform) for TPU
hardware. The reference's per-node data plane is eBPF C programs feeding a
Go agent that hash-aggregates flow events on CPU; here the data plane is a
host-side event firehose batched into fixed-shape uint32 tensor columns and
aggregated on-device by jit-compiled sketch kernels (Count-Min, HyperLogLog,
streaming entropy, heavy-hitter candidates) that merge across chips with XLA
collectives over ICI.

Package map (reference layer in parentheses, see SURVEY.md §1):

- ``events``   event record schema + sources (eBPF C programs + perf rings, L1)
- ``ops``      device hash + sketch kernels (kernel-side per-CPU map aggregation)
- ``models``   detector/aggregator models over sketches (pkg/module/metrics, L3)
- ``parallel`` mesh, shardings, collective merges (Prometheus-pull / Hubble relay
               cross-node aggregation, §2.6)
- ``enrich``   identity cache + device join (pkg/enricher + pkg/controllers/cache)
- ``plugins``  plugin registry + plugins (pkg/plugin, L2)
- ``runtime``  managers, config, pubsub, server, telemetry (pkg/managers, L4/L0)
- ``exporter`` Prometheus registries + exposition (pkg/exporter + pkg/metrics)
- ``capture``  on-demand capture orchestration (pkg/capture, L3/L6)
- ``export``   flow export / service-graph relay (pkg/hubble)
- ``orchestration`` operator-style reconcilers over an in-memory API (operator/, L6)
- ``cli``      command-line interface (cli/ kubectl-retina, L7)
- ``native``   C++ ingest path: pcap parse + SPSC ring (pkg/plugin/*/_cprog, L1)
"""

__version__ = "0.1.0"

"""Shared domain objects and pubsub topics.

Reference analog: pkg/common — RetinaEndpoint/RetinaSvc/RetinaNode identity
objects (endpoint.go), DirtyCache (dirtycache.go), pubsub topic constants
(pubsubtopics.go), apiretry.
"""

from retina_tpu.common.objects import (
    POD_ANNOTATION,
    POD_ANNOTATION_VALUE,
    DirtyCache,
    IPFamily,
    RetinaEndpoint,
    RetinaNode,
    RetinaSvc,
    retry,
)
from retina_tpu.common.topics import (
    TOPIC_APISERVER,
    TOPIC_ENDPOINTS,
    TOPIC_NAMESPACES,
    TOPIC_NODES,
    TOPIC_PODS,
    TOPIC_SERVICES,
    TOPIC_SNAPSHOT,
)

__all__ = [
    "DirtyCache",
    "IPFamily",
    "RetinaEndpoint",
    "RetinaNode",
    "RetinaSvc",
    "retry",
    "POD_ANNOTATION",
    "POD_ANNOTATION_VALUE",
    "TOPIC_APISERVER",
    "TOPIC_ENDPOINTS",
    "TOPIC_NAMESPACES",
    "TOPIC_NODES",
    "TOPIC_PODS",
    "TOPIC_SERVICES",
    "TOPIC_SNAPSHOT",
]

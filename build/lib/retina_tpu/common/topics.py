"""PubSub topic names (reference pkg/common/pubsubtopics.go)."""

TOPIC_ENDPOINTS = "endpoints"  # veth/endpoint watcher events
TOPIC_APISERVER = "apiserver"  # apiserver IP set changes
TOPIC_PODS = "pods"  # pod identity add/update/delete
TOPIC_SERVICES = "services"
TOPIC_NODES = "nodes"
TOPIC_NAMESPACES = "namespaces"  # annotated-namespace set changes
TOPIC_SNAPSHOT = "snapshot"  # sketch-state snapshot announcements

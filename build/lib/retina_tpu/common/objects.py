"""Identity domain objects, dirty-tracking cache, retry helper.

Reference analogs:
- RetinaEndpoint (pkg/common/endpoint.go): slim pod identity — name,
  namespace, IPs, labels, owner refs, containers. Thread-safety via an
  internal lock in the Go version; here instances are treated as immutable
  snapshots (replaced, never mutated) which is both simpler and what the
  device-side identity rebuild wants.
- DirtyCache (pkg/common/dirtycache.go): add/delete dirty-key tracking the
  metrics module uses to sync pod IPs into the filter map.
- retry (pkg/common/apiretry): bounded retries with backoff.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, TypeVar

T = TypeVar("T")


class IPFamily:
    IPv4 = "v4"
    IPv6 = "v6"


# Pod/namespace pod-level opt-in annotation (reference
# common/types.go:17-18): retina.sh=observe.
POD_ANNOTATION = "retina.sh"
POD_ANNOTATION_VALUE = "observe"


@dataclasses.dataclass(frozen=True)
class RetinaEndpoint:
    """Slim pod identity (reference pkg/common/endpoint.go)."""

    name: str
    namespace: str
    ips: tuple[str, ...] = ()
    labels: tuple[tuple[str, str], ...] = ()
    owner_refs: tuple[tuple[str, str], ...] = ()  # (kind, name)
    containers: tuple[str, ...] = ()
    annotations: tuple[tuple[str, str], ...] = ()
    node: str = ""

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def primary_ip(self) -> str:
        return self.ips[0] if self.ips else ""

    def workload(self) -> str:
        """Top owner ref, the reference's 'workloads' label source."""
        return self.owner_refs[0][1] if self.owner_refs else self.name

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)


@dataclasses.dataclass(frozen=True)
class RetinaSvc:
    name: str
    namespace: str
    cluster_ip: str = ""
    lb_ip: str = ""
    selector: tuple[tuple[str, str], ...] = ()

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclasses.dataclass(frozen=True)
class RetinaNode:
    name: str
    ip: str = ""
    zone: str = ""


class DirtyCache:
    """Tracks keys to add/delete since last drain (dirtycache.go)."""

    def __init__(self) -> None:
        self._to_add: dict[str, Any] = {}
        self._to_delete: dict[str, Any] = {}

    def to_add(self, key: str, obj: Any) -> None:
        self._to_delete.pop(key, None)
        self._to_add[key] = obj

    def to_delete(self, key: str, obj: Any) -> None:
        self._to_add.pop(key, None)
        self._to_delete[key] = obj

    def get_add_list(self) -> list[Any]:
        return list(self._to_add.values())

    def get_delete_list(self) -> list[Any]:
        return list(self._to_delete.values())

    def clear_add(self) -> None:
        self._to_add.clear()

    def clear_delete(self) -> None:
        self._to_delete.clear()


def retry(
    fn: Callable[[], T],
    attempts: int = 5,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    retry_on: type[BaseException] = Exception,
) -> T:
    """Exponential-backoff retry (reference pkg/common/apiretry and the
    filtermanager backoff, manager_linux.go:31-60)."""
    delay = base_delay_s
    for i in range(attempts):
        try:
            return fn()
        except retry_on:
            if i == attempts - 1:
                raise
            time.sleep(delay)
            delay = min(delay * 2, max_delay_s)
    raise AssertionError("unreachable")

"""API types: MetricsConfiguration, Capture, TracesConfiguration.

Reference analogs:
- MetricsConfiguration (crd/api/v1alpha1/metricsconfiguration_types.go:
  28-95): contextOptions (metricName + src/dst label dimensions) and
  namespace include/exclude — reconciled into the running metrics module.
- Capture (capture_types.go:53-201): targets (node/pod selectors), packet
  filters, duration/size limits, output locations; status conditions
  (:22-52).
- TracesConfiguration (tracesconfiguration_types.go:59-125).

Validation mirrors crd/api/v1alpha1/validations/.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import yaml


class ValidationError(ValueError):
    pass


# ---------------------------------------------------------------------------
# MetricsConfiguration

KNOWN_METRICS = ("forward", "drop", "tcpflags", "tcpretrans", "dns", "latency",
                 "distinct_sources", "flows", "services")
KNOWN_LABELS = ("ip", "namespace", "podname", "workload", "port", "protocol")


@dataclasses.dataclass
class MetricsContextOptions:
    metric_name: str
    src_labels: list[str] = dataclasses.field(default_factory=list)
    dst_labels: list[str] = dataclasses.field(default_factory=list)
    additional_labels: list[str] = dataclasses.field(default_factory=list)

    def validate(self) -> None:
        if self.metric_name not in KNOWN_METRICS:
            raise ValidationError(
                f"unknown metric {self.metric_name!r} (known: {KNOWN_METRICS})"
            )
        for lbl in (*self.src_labels, *self.dst_labels):
            if lbl not in KNOWN_LABELS:
                raise ValidationError(
                    f"unknown label {lbl!r} for metric {self.metric_name}"
                )


@dataclasses.dataclass
class MetricsNamespaces:
    include: list[str] = dataclasses.field(default_factory=list)
    exclude: list[str] = dataclasses.field(default_factory=list)

    def validate(self) -> None:
        if self.include and self.exclude:
            raise ValidationError(
                "namespaces.include and namespaces.exclude are exclusive"
            )

    def admits(self, ns: str) -> bool:
        if self.include:
            return ns in self.include
        return ns not in self.exclude


@dataclasses.dataclass
class MetricsSpec:
    context_options: list[MetricsContextOptions] = dataclasses.field(
        default_factory=list
    )
    namespaces: MetricsNamespaces = dataclasses.field(
        default_factory=MetricsNamespaces
    )

    def validate(self) -> None:
        seen = set()
        for co in self.context_options:
            co.validate()
            if co.metric_name in seen:
                raise ValidationError(
                    f"duplicate contextOption for {co.metric_name}"
                )
            seen.add(co.metric_name)
        self.namespaces.validate()


@dataclasses.dataclass
class MetricsConfiguration:
    name: str = "default"
    # Kept for CRDStore keying (ns/name): without it, a CR outside the
    # "default" namespace is stored under the wrong key and the bridge's
    # post-LIST resync deletes it right after applying it.
    namespace: str = "default"
    spec: MetricsSpec = dataclasses.field(default_factory=MetricsSpec)

    def validate(self) -> None:
        self.spec.validate()

    @classmethod
    def default(cls) -> "MetricsConfiguration":
        """The out-of-the-box pod-level metric set (reference helm
        defaults: forward/drop/dns/tcp in local context)."""
        return cls(
            spec=MetricsSpec(
                context_options=[
                    MetricsContextOptions("forward", ["podname", "namespace"]),
                    MetricsContextOptions("drop", ["podname", "namespace"]),
                    MetricsContextOptions("tcpflags", ["podname", "namespace"]),
                    MetricsContextOptions("tcpretrans", ["podname", "namespace"]),
                    MetricsContextOptions("dns", ["podname", "namespace"]),
                    MetricsContextOptions("latency", []),
                    MetricsContextOptions("distinct_sources",
                                          ["podname", "namespace"]),
                    MetricsContextOptions("flows", []),
                    MetricsContextOptions("services", []),
                ]
            )
        )

    @classmethod
    def from_yaml(cls, text: str) -> "MetricsConfiguration":
        doc = yaml.safe_load(text) or {}
        spec_doc = doc.get("spec", doc)
        cos = [
            MetricsContextOptions(
                metric_name=c.get("metricName", c.get("metric_name", "")),
                src_labels=c.get("sourceLabels", c.get("src_labels", [])),
                dst_labels=c.get("destinationLabels", c.get("dst_labels", [])),
                additional_labels=c.get("additionalLabels",
                                        c.get("additional_labels", [])),
            )
            for c in spec_doc.get("contextOptions", [])
        ]
        ns_doc = spec_doc.get("namespaces", {}) or {}
        meta = doc.get("metadata", {}) or {}
        obj = cls(
            name=meta.get("name", "default"),
            namespace=meta.get("namespace") or "default",
            spec=MetricsSpec(
                context_options=cos,
                namespaces=MetricsNamespaces(
                    include=ns_doc.get("include") or [],
                    exclude=ns_doc.get("exclude") or [],
                ),
            ),
        )
        obj.validate()
        return obj


# ---------------------------------------------------------------------------
# Capture

MAX_CAPTURE_DURATION_S = 3600  # capture_types.go duration ceiling


@dataclasses.dataclass
class CaptureTarget:
    """Node/pod selection (capture_types.go CaptureTarget)."""

    node_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    node_names: list[str] = dataclasses.field(default_factory=list)
    pod_selector: dict[str, str] = dataclasses.field(default_factory=dict)
    namespace_selector: dict[str, str] = dataclasses.field(
        default_factory=dict
    )

    def validate(self) -> None:
        has_node = bool(self.node_selector or self.node_names)
        has_pod = bool(self.pod_selector or self.namespace_selector)
        if not has_node and not has_pod:
            raise ValidationError(
                "capture target needs a node selector or a pod selector"
            )
        if has_node and has_pod:
            raise ValidationError(
                "node and pod selectors are mutually exclusive"
            )


@dataclasses.dataclass
class CaptureOutput:
    """Output sinks (capture_types.go OutputConfiguration)."""

    host_path: str = ""
    persistent_volume_claim: str = ""
    blob_upload_secret: str = ""
    s3_upload: dict[str, str] = dataclasses.field(default_factory=dict)

    def is_empty(self) -> bool:
        """No output location configured (the managed-storage gate and
        the translator's job-time guard share this predicate)."""
        return not (self.host_path or self.persistent_volume_claim
                    or self.blob_upload_secret or self.s3_upload)

    def validate(self) -> None:
        # An EMPTY output is admissible: the reference CRD does not
        # require one, because the operator's managed-storage path fills
        # BlobUpload in during reconcile (controller.go:310-350 /
        # capture/managed.py). Translation enforces that SOME output
        # exists by job-creation time (translator.py).
        if self.s3_upload:
            for req in ("bucket", "region"):
                if req not in self.s3_upload:
                    raise ValidationError(f"s3Upload missing {req!r}")


@dataclasses.dataclass
class CaptureSpec:
    target: CaptureTarget = dataclasses.field(default_factory=CaptureTarget)
    output: CaptureOutput = dataclasses.field(default_factory=CaptureOutput)
    duration_s: int = 60
    max_capture_size_mb: int = 100
    packet_size_bytes: int = 0  # 0 = full packets
    tcpdump_filter: str = ""  # raw extra filter
    include_metadata: bool = True

    def validate(self) -> None:
        if not (0 < self.duration_s <= MAX_CAPTURE_DURATION_S):
            raise ValidationError(
                f"duration must be in (0, {MAX_CAPTURE_DURATION_S}]s"
            )
        self.target.validate()
        self.output.validate()


@dataclasses.dataclass
class CaptureStatus:
    """Status conditions (capture_types.go:22-52)."""

    phase: str = "Pending"  # Pending | Running | Completed | Failed
    jobs_active: int = 0
    jobs_completed: int = 0
    jobs_failed: int = 0
    message: str = ""
    artifacts: list[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class Capture:
    name: str
    namespace: str = "default"
    spec: CaptureSpec = dataclasses.field(default_factory=CaptureSpec)
    status: CaptureStatus = dataclasses.field(default_factory=CaptureStatus)

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("capture needs a name")
        self.spec.validate()

    @classmethod
    def from_yaml(cls, text: str) -> "Capture":
        doc = yaml.safe_load(text) or {}
        meta = doc.get("metadata", {})
        s = doc.get("spec", {})
        tgt = s.get("captureConfiguration", s).get("captureTarget",
                                                   s.get("target", {}))
        out = s.get("outputConfiguration", s.get("output", {}))
        obj = cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            spec=CaptureSpec(
                target=CaptureTarget(
                    node_selector=tgt.get("nodeSelector", {}).get(
                        "matchLabels", tgt.get("nodeSelector", {})
                    ) if isinstance(tgt.get("nodeSelector", {}), dict) else {},
                    node_names=tgt.get("nodeNames", []),
                    pod_selector=tgt.get("podSelector", {}).get(
                        "matchLabels", tgt.get("podSelector", {})
                    ) if isinstance(tgt.get("podSelector", {}), dict) else {},
                    namespace_selector=tgt.get("namespaceSelector", {}).get(
                        "matchLabels", tgt.get("namespaceSelector", {})
                    ) if isinstance(tgt.get("namespaceSelector", {}), dict)
                    else {},
                ),
                output=CaptureOutput(
                    host_path=out.get("hostPath", ""),
                    persistent_volume_claim=out.get("persistentVolumeClaim", ""),
                    blob_upload_secret=out.get("blobUpload", ""),
                    s3_upload=out.get("s3Upload", {}),
                ),
                duration_s=int(s.get("captureConfiguration", s).get(
                    "captureOption", {}).get("duration", s.get("duration", 60))
                ) if isinstance(s.get("duration", 60), (int, str)) else 60,
                tcpdump_filter=s.get("captureConfiguration", s).get(
                    "filters", {}).get("raw", s.get("tcpdumpFilter", ""))
                if isinstance(s.get("tcpdumpFilter", ""), str) else "",
            ),
        )
        # Preserve status if the document carries one: objects echoed back
        # by a backend (apiserver watch after our own status PATCH, or a
        # re-LIST of already-Completed captures) must NOT reset to Pending,
        # or the operator would re-run finished captures forever.
        st = doc.get("status") or {}
        if st:
            obj.status = CaptureStatus(
                phase=st.get("phase", "Pending"),
                jobs_active=int(st.get("jobs_active",
                                       st.get("jobsActive", 0)) or 0),
                jobs_completed=int(st.get("jobs_completed",
                                          st.get("jobsCompleted", 0)) or 0),
                jobs_failed=int(st.get("jobs_failed",
                                       st.get("jobsFailed", 0)) or 0),
                message=st.get("message", ""),
                artifacts=list(st.get("artifacts", [])),
            )
        obj.validate()
        return obj


# ---------------------------------------------------------------------------
# TracesConfiguration (stub parity: reference module is a skeleton too)


@dataclasses.dataclass
class TracesSpec:
    trace_targets: list[dict[str, Any]] = dataclasses.field(
        default_factory=list
    )
    trace_points: list[str] = dataclasses.field(default_factory=list)
    sampling_rate_per_mille: int = 0


@dataclasses.dataclass
class TracesConfiguration:
    name: str = "default"
    namespace: str = "default"  # CRDStore keying (see MetricsConfiguration)
    spec: TracesSpec = dataclasses.field(default_factory=TracesSpec)

    @classmethod
    def from_yaml(cls, text: str) -> "TracesConfiguration":
        # Null-tolerant throughout: a CR with `traceTargets:` left
        # empty (YAML null) must parse as [], not raise inside the
        # bridge's LIST loop — one malformed CR would wedge the whole
        # kind's watch in a re-LIST spin.
        doc = yaml.safe_load(text) or {}
        meta = doc.get("metadata", {}) or {}
        s = doc.get("spec", {}) or {}
        return cls(
            name=meta.get("name", "default"),
            namespace=meta.get("namespace") or "default",
            spec=TracesSpec(
                trace_targets=list(
                    s.get("traceTargets")
                    or s.get("trace_targets") or []
                ),
                trace_points=list(
                    s.get("tracePoints") or s.get("trace_points") or []
                ),
                sampling_rate_per_mille=int(
                    s.get("samplingRatePerMille")
                    or s.get("sampling_rate_per_mille") or 0
                ),
            ),
        )

"""CRD-shaped API types (reference crd/api/v1alpha1).

No kube-apiserver exists here, so "CRDs" are dataclasses with the same
shape + validation rules, loadable from YAML (the operator and CLI consume
them the way the reference's controllers consume CRs).
"""

from retina_tpu.crd.types import (
    Capture,
    CaptureOutput,
    CaptureSpec,
    CaptureStatus,
    CaptureTarget,
    MetricsConfiguration,
    MetricsContextOptions,
    MetricsNamespaces,
    MetricsSpec,
    TracesConfiguration,
    TracesSpec,
    ValidationError,
)

__all__ = [
    "Capture",
    "CaptureOutput",
    "CaptureSpec",
    "CaptureStatus",
    "CaptureTarget",
    "MetricsConfiguration",
    "MetricsContextOptions",
    "MetricsNamespaces",
    "MetricsSpec",
    "TracesConfiguration",
    "TracesSpec",
    "ValidationError",
]

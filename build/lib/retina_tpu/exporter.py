"""Prometheus exporter registries.

Reference analog: pkg/exporter/prometheusexporter.go:17-40 — three
registries: **Default** (basic node-level metrics, lives for the process),
**Advanced** (pod-level metrics, RESET whenever a MetricsConfiguration CRD
reconcile changes the metric set, :35-40), and a **Combined** gatherer the
HTTP server scrapes. Constructor helpers mirror :46-88.

Built on prometheus_client's CollectorRegistry; the combined gatherer is a
merge of both registries' samples at scrape time, and reset callbacks let
the HTTP server re-register its handler like the reference does.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram

from retina_tpu.log import logger

_log = logger("exporter")


_INF = float("inf")


def _escape_label(v: str) -> str:
    # The common case (no escapable chars) must cost containment
    # checks, not three regex passes per sample like prometheus_client.
    if "\\" in v:
        v = v.replace("\\", "\\\\")
    if "\n" in v:
        v = v.replace("\n", "\\n")
    if '"' in v:
        v = v.replace('"', '\\"')
    return v


def _float_str(d: float) -> str:
    """prometheus_client.utils.floatToGoString, regex-free."""
    d = float(d)
    if d == _INF:
        return "+Inf"
    if d == -_INF:
        return "-Inf"
    if d != d:
        return "NaN"
    s = repr(d)
    dot = s.find(".")
    if d > 0 and dot > 6:
        mantissa = f"{s[0]}.{s[1:dot]}{s[dot + 1:]}".rstrip("0.")
        return f"{mantissa}e+0{dot - 1}"
    return s


def _sample_line(s) -> str:
    if s.labels:
        lbl = ",".join(
            f'{k}="{_escape_label(v)}"'
            for k, v in sorted(s.labels.items())
        )
        labelstr = "{" + lbl + "}"
    else:
        labelstr = ""
    if s.timestamp is not None:
        ts = f" {int(float(s.timestamp) * 1000):d}"
    else:
        ts = ""
    return f"{s.name}{labelstr} {_float_str(s.value)}{ts}\n"


def render_exposition(registry: CollectorRegistry) -> bytes:
    """Fast Prometheus text-format renderer (text/plain; version 0.0.4).

    Byte-identical to prometheus_client.generate_latest for the metric
    and label NAMES this framework emits (valid legacy identifiers by
    construction). The library routes every sample through three
    regex-validation/escaping passes — ~1.1s per render at 30k pod-level
    samples, the agent's single largest CPU cost under scrape load; this
    writer emits the same bytes with plain string operations. The test
    suite cross-checks byte equality against generate_latest.
    """
    output: list[str] = []
    for metric in registry.collect():
        mname = metric.name
        mtype = metric.type
        if mtype == "counter":
            mname += "_total"
        elif mtype == "info":
            mname += "_info"
            mtype = "gauge"
        elif mtype == "stateset":
            mtype = "gauge"
        elif mtype == "gaugehistogram":
            mtype = "histogram"
        elif mtype == "unknown":
            mtype = "untyped"
        doc = metric.documentation.replace("\\", r"\\").replace(
            "\n", r"\n"
        )
        output.append(f"# HELP {mname} {doc}\n")
        output.append(f"# TYPE {mname} {mtype}\n")
        om_samples: dict[str, list[str]] = {}
        base = metric.name
        for s in metric.samples:
            name = s.name
            if (
                name == base + "_created"
                or name == base + "_gsum"
                or name == base + "_gcount"
            ):
                om_samples.setdefault(name[len(base):], []).append(
                    _sample_line(s)
                )
            else:
                output.append(_sample_line(s))
        for suffix, lines in sorted(om_samples.items()):
            output.append(f"# HELP {base}{suffix} {doc}\n")
            output.append(f"# TYPE {base}{suffix} gauge\n")
            output.extend(lines)
    return "".join(output).encode("utf-8")


class Exporter:
    """Holds the default + advanced registries (reference package state)."""

    def __init__(self) -> None:
        self.default_registry = CollectorRegistry()
        self.advanced_registry = CollectorRegistry()
        # Hubble self-metrics live in their OWN registry, served by the
        # dedicated hubble metrics mux (reference :9965) and NOT by the
        # combined gatherer — scraping both muxes must not double-ingest.
        self.hubble_registry = CollectorRegistry()
        self._reset_cbs: list[Callable[[], None]] = []
        self._lock = threading.Lock()

    # -- reset (prometheusexporter.go:35-40) --
    def reset_advanced(self) -> None:
        """Replace the advanced registry (CRD reconcile changed metrics)."""
        with self._lock:
            self.advanced_registry = CollectorRegistry()
            cbs = list(self._reset_cbs)
        _log.info("advanced metrics registry reset")
        for cb in cbs:
            cb()

    def on_reset(self, cb: Callable[[], None]) -> None:
        with self._lock:
            self._reset_cbs.append(cb)

    # -- combined gatherer (prometheusexporter.go:17-33) --
    def gather_text(self) -> bytes:
        """Prometheus text exposition of both registries.

        Rendered by :func:`render_exposition`, not prometheus_client's
        generate_latest: at production cardinality (~30k pod-level
        samples) the library's per-sample regex validation/escaping cost
        ~1.1s per render on one core — over half the agent's CPU under
        scrape load. The fast path emits the same text format ~10x
        cheaper; a round-trip test pins it byte-compatible.
        """
        with self._lock:
            regs: Iterable[CollectorRegistry] = (
                self.default_registry,
                self.advanced_registry,
            )
        return b"".join(render_exposition(r) for r in regs)

    # -- constructor helpers (prometheusexporter.go:46-88) --
    def new_gauge(self, name: str, labels: list[str], help_: str = "") -> Gauge:
        return Gauge(
            name, help_ or name, labels, registry=self.default_registry
        )

    def new_counter(self, name: str, labels: list[str], help_: str = "") -> Counter:
        return Counter(
            name, help_ or name, labels, registry=self.default_registry
        )

    def new_histogram(
        self, name: str, labels: list[str], buckets: list[float], help_: str = ""
    ) -> Histogram:
        return Histogram(
            name, help_ or name, labels,
            buckets=buckets, registry=self.default_registry,
        )

    def gather_hubble_text(self) -> bytes:
        """Exposition of the hubble registry only (:9965 mux)."""
        return render_exposition(self.hubble_registry)

    def new_hubble_gauge(self, name: str, labels: list[str],
                         help_: str = "") -> Gauge:
        return Gauge(
            name, help_ or name, labels, registry=self.hubble_registry
        )

    def new_hubble_counter(self, name: str, labels: list[str],
                           help_: str = "") -> Counter:
        return Counter(
            name, help_ or name, labels, registry=self.hubble_registry
        )

    def new_adv_gauge(self, name: str, labels: list[str], help_: str = "") -> Gauge:
        with self._lock:
            reg = self.advanced_registry
        return Gauge(name, help_ or name, labels, registry=reg)

    def new_adv_counter(
        self, name: str, labels: list[str], help_: str = ""
    ) -> Counter:
        with self._lock:
            reg = self.advanced_registry
        return Counter(name, help_ or name, labels, registry=reg)


_singleton: Exporter | None = None
_lock = threading.Lock()


def get_exporter() -> Exporter:
    global _singleton
    with _lock:
        if _singleton is None:
            _singleton = Exporter()
        return _singleton


def reset_for_tests() -> None:
    """Fresh registries so tests don't collide on metric names."""
    global _singleton
    with _lock:
        _singleton = None

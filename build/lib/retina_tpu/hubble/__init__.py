"""Hubble-analog flow control plane (reference pkg/hubble, pkg/monitoragent).

The reference's second control plane streams enriched flows over gRPC
(:4244 relay, unix socket locally) from a ring-buffer observer fed by the
monitor agent. Same architecture here:

- monitoragent: drains the plugins' external channel, fans out to
  consumers (pkg/monitoragent).
- flow: record → flow-dict decoding with identity enrichment (pkg/hubble/
  parser layer34 + seven/DNS).
- observer: fixed-capacity flow ring with follow cursors (the Cilium
  container.Ring analog) + filter evaluation.
- server/client: the gRPC flow relay. The image has no protoc-gen-grpc,
  so services use gRPC generic handlers with msgpack frames instead of
  protobuf codegen — the transport is still gRPC/HTTP2 streaming.
"""

from retina_tpu.hubble.monitoragent import MonitorAgent
from retina_tpu.hubble.observer import FlowObserver
from retina_tpu.hubble.server import HubbleServer

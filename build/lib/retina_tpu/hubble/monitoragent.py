"""Monitor agent: external-channel drain + consumer fan-out.

Reference analog: pkg/monitoragent/monitoragent_linux.go — plugins push
events into the external channel handed out by SetupChannel
(pluginmanager.go:206-212); the monitor agent's SendEvent fans each event
out to registered consumers (:46-47, :160) — the Hubble observer chief
among them. Identical contract here over record blocks.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable

import numpy as np

from retina_tpu.log import logger

Consumer = Callable[[np.ndarray], None]


class MonitorAgent:
    def __init__(self, channel_depth: int = 256):
        self._log = logger("monitoragent")
        self.channel: queue.Queue[np.ndarray] = queue.Queue(
            maxsize=channel_depth
        )
        self._consumers: list[Consumer] = []
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def register_consumer(self, fn: Consumer) -> None:
        with self._lock:
            self._consumers.append(fn)

    def send_event(self, records: np.ndarray) -> None:
        """Direct injection (SendEvent analog)."""
        with self._lock:
            consumers = list(self._consumers)
        for c in consumers:
            try:
                c(records)
            except Exception:
                self._log.exception("consumer failed")

    def start(self, stop: threading.Event) -> None:
        def drain() -> None:
            while not stop.is_set():
                try:
                    block = self.channel.get(timeout=0.2)
                except queue.Empty:
                    continue
                self.send_event(block)

        self._thread = threading.Thread(
            target=drain, name="monitoragent", daemon=True
        )
        self._thread.start()

"""Flow observer: bounded flow ring with follow readers.

Reference analog: the Hubble observer's ring buffer of decoded flows that
``GetFlows`` serves, with follow semantics (new flows stream as they
arrive) — the same structure the enricher uses internally (Cilium
container.Ring, enricher.go:45-52: bounded, overwrite-oldest, per-reader
cursors that observe loss rather than block the writer).
"""

from __future__ import annotations

import threading
from typing import Any, Iterator, Optional

import numpy as np

from retina_tpu.hubble.flow import FlowFilter, record_to_flow
from retina_tpu.log import logger


class FlowObserver:
    def __init__(self, capacity: int = 4096, cache: Any = None,
                 dns_resolver: Any = None):
        assert capacity & (capacity - 1) == 0
        self._log = logger("observer")
        self._cap = capacity
        self._ring: list[Optional[dict]] = [None] * capacity
        self._seq = 0  # total flows ever written
        self._lock = threading.Condition()
        self.cache = cache
        self.dns_resolver = dns_resolver
        self.flows_seen = 0
        # Ring entries skipped by lagging readers, summed across readers
        # (per-reader loss is ALSO surfaced in-stream as LostEvent
        # markers; this aggregate only feeds the self-metric gauge).
        self.lost_observed = 0

    # -- writer side (monitoragent consumer) ---------------------------
    def consume(self, records: np.ndarray) -> None:
        """Write raw record rows; decode is LAZY (on read).

        The writer sits on the hot mirror path (every flow the engine
        sees), while readers are few and slow (gRPC streams). Eager
        per-record dict decode capped the writer at ~0.15M flows/s;
        storing (block, row) refs moves the ~µs decode to the reader,
        which only ever materializes the ≤capacity flows it serves."""
        with self._lock:
            for i in range(len(records)):
                self._ring[self._seq & (self._cap - 1)] = (records, i)
                self._seq += 1
            self.flows_seen = self._seq
            self._lock.notify_all()

    def consume_flows(self, flows: list[dict]) -> None:
        """Write already-decoded flow dicts (relay peer ingestion)."""
        with self._lock:
            for f in flows:
                self._ring[self._seq & (self._cap - 1)] = f
                self._seq += 1
            self.flows_seen = self._seq
            self._lock.notify_all()

    # -- lazy decode ----------------------------------------------------
    def _materialize(self, entry, seq: Optional[int] = None) -> dict:
        """Decode a raw ring entry to a flow dict, memoizing the result
        back into the ring slot (decode once, however many readers).

        Semantics note: identity/DNS enrichment happens at FIRST READ,
        not at arrival — if a pod IP is recycled while a flow sits
        unread in the ring, the flow gets the current owner's identity.
        The skew window is bounded by ring residency (capacity flows,
        well under a second at production rates); upstream Hubble has
        the same property between its own ring and its ipcache."""
        if isinstance(entry, tuple):  # (records_block, row_index)
            block, i = entry
            f = record_to_flow(block[i], self.cache, self.dns_resolver)
            if seq is not None:
                with self._lock:
                    slot = seq & (self._cap - 1)
                    if self._ring[slot] is entry:
                        self._ring[slot] = f
            return f
        return entry

    # -- reader side ---------------------------------------------------
    def snapshot_flows(self) -> tuple[list[dict], int]:
        """All currently-buffered flows (oldest first) + the sequence
        cursor to continue from with :meth:`follow_from`. Servers filter
        this list THEN apply last-N windowing, matching upstream Hubble's
        'N most recent matching flows' semantics."""
        with self._lock:
            end = self._seq
            window = min(end, self._cap)
            entries = [
                (i, self._ring[i & (self._cap - 1)])
                for i in range(end - window, end)
            ]
        # Materialize OUTSIDE the lock: decode must never stall writers.
        return [self._materialize(e, seq) for seq, e in entries
                if e is not None], end

    def follow_from(
        self,
        cursor: int,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[tuple[str, Any]]:
        """Follow the ring from ``cursor``: yields ("flow", flow) items
        and ("lost", n) markers when this reader fell behind (the
        upstream in-stream LostEvent contract)."""
        while stop is None or not stop.is_set():
            batch: list = []
            lost = 0
            with self._lock:
                floor = self._seq - self._cap
                if cursor < floor:
                    lost = floor - cursor
                    self.lost_observed += lost
                    cursor = floor
                while cursor < self._seq:
                    f = self._ring[cursor & (self._cap - 1)]
                    if f is not None:
                        batch.append((cursor, f))
                    cursor += 1
                if not batch and not lost:
                    self._lock.wait(timeout=0.2)
            if lost:
                yield ("lost", lost)
            for seq, f in batch:
                yield ("flow", self._materialize(f, seq))

    def get_flows(
        self,
        filter: Optional[FlowFilter] = None,
        last: int = 0,
        follow: bool = False,
        stop: Optional[threading.Event] = None,
        timeout_s: float = 30.0,
        lost_markers: bool = False,
    ) -> Iterator[dict[str, Any]]:
        """Yield flows: the most recent ``last`` (0 = all buffered), then
        keep following if requested. A slow reader skips overwritten
        entries (loss over blocking, like every ring in this system);
        with ``lost_markers`` each skip also yields a
        ``{"lost_events": n}`` marker (the msgpack analog of the
        protobuf surface's LostEvent response) that bypasses the filter
        — consumers distinguish markers by that key."""
        with self._lock:
            end0 = self._seq
            window = min(end0, self._cap, last if last else self._cap)
            cursor = end0 - window
        # Initial buffered window: one bounded scan (a lap between the
        # snapshot and this scan surfaces as a marker too).
        skipped = 0
        with self._lock:
            floor = self._seq - self._cap
            if cursor < floor:
                skipped = floor - cursor
                self.lost_observed += skipped
                cursor = floor
            batch = []
            while cursor < end0:
                f = self._ring[cursor & (self._cap - 1)]
                if f is not None:
                    batch.append((cursor, f))
                cursor += 1
        if skipped and lost_markers:
            yield {"lost_events": int(skipped)}
        for seq, f in batch:
            f = self._materialize(f, seq)
            if filter is None or filter.matches(f):
                yield f
        if not follow:
            return
        # Follow phase: ONE implementation of the skip/account/emit
        # contract lives in follow_from (also the protobuf surface's
        # engine); this just maps its items onto the dict stream.
        for kind, payload in self.follow_from(cursor, stop):
            if stop is not None and stop.is_set():
                return
            if kind == "lost":
                if lost_markers:
                    yield {"lost_events": int(payload)}
            elif filter is None or filter.matches(payload):
                yield payload

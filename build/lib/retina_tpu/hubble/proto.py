"""Cilium Hubble wire-compatible protobuf messages, built at runtime.

Reference analog: pkg/hubble serves the Cilium Observer API — protobuf
messages from cilium's api/v1/{flow/flow.proto, observer/observer.proto,
peer/peer.proto} over gRPC (hubble_linux.go:52-99). This image has no
protoc and no cilium python package, but it does have google.protobuf, so
the descriptors are hand-rolled here as FileDescriptorProtos with the
SAME package/message/field names and FIELD NUMBERS as upstream (the
subset Retina populates — cilium/cilium api/v1/flow/flow.proto field
numbering: time=1, verdict=2, IP=5, l4=6, source=8, destination=9,
Type=10, node_name=11, l7=15, event_type=19, traffic_direction=24,
is_reply=28, uuid=34). A stock Hubble client (hubble CLI / relay) speaks
this wire format: method names `/observer.Observer/GetFlows`,
`/observer.Observer/ServerStatus`, `/peer.Peer/Notify`.

Unknown-to-us upstream fields are simply absent (proto3 semantics make
them defaults); fields we emit decode correctly on any conforming client.
"""

from __future__ import annotations

from typing import Any

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory
from google.protobuf import timestamp_pb2, wrappers_pb2  # noqa: F401 (deps)

_T = descriptor_pb2.FieldDescriptorProto

_pool = descriptor_pool.DescriptorPool()
# google well-known types must exist in our private pool.
for wkt in (timestamp_pb2, wrappers_pb2):
    fdp = descriptor_pb2.FileDescriptorProto()
    wkt.DESCRIPTOR.CopyToProto(fdp)
    _pool.Add(fdp)


def _field(name: str, number: int, ftype: int, label: int = 1,
           type_name: str = "", oneof_index: int | None = None):
    f = _T(name=name, number=number, type=ftype, label=label)
    if type_name:
        f.type_name = type_name
    if oneof_index is not None:
        f.oneof_index = oneof_index
    return f


def _msg(name: str, fields: list, oneofs: list[str] | None = None):
    m = descriptor_pb2.DescriptorProto(name=name)
    m.field.extend(fields)
    for o in oneofs or []:
        m.oneof_decl.add(name=o)
    return m


def _enum(name: str, values: dict[str, int]):
    e = descriptor_pb2.EnumDescriptorProto(name=name)
    for vname, vnum in values.items():
        e.value.add(name=vname, number=vnum)
    return e


_TS = ".google.protobuf.Timestamp"
_BOOLV = ".google.protobuf.BoolValue"

# ---------------------------------------------------------------------
# flow.proto (package flow) — upstream cilium/api/v1/flow/flow.proto
# ---------------------------------------------------------------------
_flow_fdp = descriptor_pb2.FileDescriptorProto(
    name="flow/flow.proto",
    package="flow",
    syntax="proto3",
    dependency=["google/protobuf/timestamp.proto",
                "google/protobuf/wrappers.proto"],
)
_flow_fdp.enum_type.extend([
    _enum("FlowType", {"UNKNOWN_TYPE": 0, "L3_L4": 1, "L7": 2, "SOCK": 3}),
    _enum("Verdict", {
        "VERDICT_UNKNOWN": 0, "FORWARDED": 1, "DROPPED": 2, "ERROR": 3,
        "AUDIT": 4, "REDIRECTED": 5, "TRACED": 6, "TRANSLATED": 7,
    }),
    _enum("TrafficDirection", {
        "TRAFFIC_DIRECTION_UNKNOWN": 0, "INGRESS": 1, "EGRESS": 2,
    }),
    _enum("IPVersion", {"IP_NOT_USED": 0, "IPv4": 1, "IPv6": 2}),
    _enum("L7FlowType", {
        "UNKNOWN_L7_TYPE": 0, "REQUEST": 1, "RESPONSE": 2, "SAMPLE": 3,
    }),
])
_flow_fdp.message_type.extend([
    _msg("IP", [
        _field("source", 1, _T.TYPE_STRING),
        _field("destination", 2, _T.TYPE_STRING),
        _field("ipVersion", 3, _T.TYPE_ENUM, type_name=".flow.IPVersion"),
    ]),
    _msg("TCPFlags", [
        _field(n, i + 1, _T.TYPE_BOOL) for i, n in enumerate(
            ["FIN", "SYN", "RST", "PSH", "ACK", "URG", "ECE", "CWR", "NS"]
        )
    ]),
    _msg("TCP", [
        _field("source_port", 1, _T.TYPE_UINT32),
        _field("destination_port", 2, _T.TYPE_UINT32),
        _field("flags", 3, _T.TYPE_MESSAGE, type_name=".flow.TCPFlags"),
    ]),
    _msg("UDP", [
        _field("source_port", 1, _T.TYPE_UINT32),
        _field("destination_port", 2, _T.TYPE_UINT32),
    ]),
    _msg("ICMPv4", [
        _field("type", 1, _T.TYPE_UINT32),
        _field("code", 2, _T.TYPE_UINT32),
    ]),
    _msg("Layer4", [
        _field("TCP", 1, _T.TYPE_MESSAGE, type_name=".flow.TCP",
               oneof_index=0),
        _field("UDP", 2, _T.TYPE_MESSAGE, type_name=".flow.UDP",
               oneof_index=0),
        _field("ICMPv4", 3, _T.TYPE_MESSAGE, type_name=".flow.ICMPv4",
               oneof_index=0),
    ], oneofs=["protocol"]),
    _msg("Workload", [
        _field("name", 1, _T.TYPE_STRING),
        _field("kind", 2, _T.TYPE_STRING),
    ]),
    _msg("Endpoint", [
        _field("ID", 1, _T.TYPE_UINT32),
        _field("identity", 2, _T.TYPE_UINT32),
        _field("namespace", 3, _T.TYPE_STRING),
        _field("labels", 4, _T.TYPE_STRING, label=3),
        _field("pod_name", 5, _T.TYPE_STRING),
        _field("workloads", 6, _T.TYPE_MESSAGE, label=3,
               type_name=".flow.Workload"),
        _field("cluster_name", 7, _T.TYPE_STRING),
    ]),
    _msg("DNS", [
        _field("query", 1, _T.TYPE_STRING),
        _field("ips", 2, _T.TYPE_STRING, label=3),
        _field("ttl", 3, _T.TYPE_UINT32),
        _field("cnames", 4, _T.TYPE_STRING, label=3),
        _field("observation_source", 5, _T.TYPE_STRING),
        _field("rcode", 6, _T.TYPE_UINT32),
        _field("qtypes", 7, _T.TYPE_STRING, label=3),
        _field("rrtypes", 8, _T.TYPE_STRING, label=3),
    ]),
    _msg("Layer7", [
        _field("type", 1, _T.TYPE_ENUM, type_name=".flow.L7FlowType"),
        _field("latency_ns", 2, _T.TYPE_UINT64),
        _field("dns", 100, _T.TYPE_MESSAGE, type_name=".flow.DNS",
               oneof_index=0),
    ], oneofs=["record"]),
    _msg("CiliumEventType", [
        _field("type", 1, _T.TYPE_INT32),
        _field("sub_type", 2, _T.TYPE_INT32),
    ]),
    _msg("Flow", [
        _field("time", 1, _T.TYPE_MESSAGE, type_name=_TS),
        _field("verdict", 2, _T.TYPE_ENUM, type_name=".flow.Verdict"),
        _field("drop_reason", 3, _T.TYPE_UINT32),
        _field("IP", 5, _T.TYPE_MESSAGE, type_name=".flow.IP"),
        _field("l4", 6, _T.TYPE_MESSAGE, type_name=".flow.Layer4"),
        _field("source", 8, _T.TYPE_MESSAGE, type_name=".flow.Endpoint"),
        _field("destination", 9, _T.TYPE_MESSAGE,
               type_name=".flow.Endpoint"),
        _field("Type", 10, _T.TYPE_ENUM, type_name=".flow.FlowType"),
        _field("node_name", 11, _T.TYPE_STRING),
        _field("source_names", 13, _T.TYPE_STRING, label=3),
        _field("destination_names", 14, _T.TYPE_STRING, label=3),
        _field("l7", 15, _T.TYPE_MESSAGE, type_name=".flow.Layer7"),
        _field("reply", 16, _T.TYPE_BOOL),
        _field("event_type", 19, _T.TYPE_MESSAGE,
               type_name=".flow.CiliumEventType"),
        _field("traffic_direction", 24, _T.TYPE_ENUM,
               type_name=".flow.TrafficDirection"),
        _field("drop_reason_desc", 27, _T.TYPE_UINT32),
        _field("is_reply", 28, _T.TYPE_MESSAGE, type_name=_BOOLV),
        _field("uuid", 34, _T.TYPE_STRING),
        _field("Summary", 100000, _T.TYPE_STRING),
    ]),
    _msg("FlowFilter", [
        _field("uuid", 29, _T.TYPE_STRING, label=3),
        _field("source_ip", 1, _T.TYPE_STRING, label=3),
        _field("source_pod", 2, _T.TYPE_STRING, label=3),
        _field("destination_ip", 5, _T.TYPE_STRING, label=3),
        _field("destination_pod", 6, _T.TYPE_STRING, label=3),
        _field("verdict", 9, _T.TYPE_ENUM, label=3,
               type_name=".flow.Verdict"),
        _field("source_port", 11, _T.TYPE_STRING, label=3),
        _field("destination_port", 12, _T.TYPE_STRING, label=3),
        _field("protocol", 15, _T.TYPE_STRING, label=3),
    ]),
    _msg("LostEvent", [
        _field("source", 1, _T.TYPE_ENUM,
               type_name=".flow.LostEventSource"),
        _field("num_events_lost", 2, _T.TYPE_UINT64),
    ]),
])
_flow_fdp.enum_type.add(name="LostEventSource").value.add(
    name="UNKNOWN_LOST_EVENT_SOURCE", number=0)
_flow_fdp.enum_type[-1].value.add(name="PERF_EVENT_RING_BUFFER", number=1)
_flow_fdp.enum_type[-1].value.add(name="OBSERVER_EVENTS_QUEUE", number=2)
_flow_fdp.enum_type[-1].value.add(name="HUBBLE_RING_BUFFER", number=3)
_pool.Add(_flow_fdp)

# ---------------------------------------------------------------------
# observer.proto (package observer)
# ---------------------------------------------------------------------
_obs_fdp = descriptor_pb2.FileDescriptorProto(
    name="observer/observer.proto",
    package="observer",
    syntax="proto3",
    dependency=["flow/flow.proto", "google/protobuf/timestamp.proto"],
)
_obs_fdp.message_type.extend([
    _msg("GetFlowsRequest", [
        _field("number", 1, _T.TYPE_UINT64),
        _field("whitelist", 2, _T.TYPE_MESSAGE, label=3,
               type_name=".flow.FlowFilter"),
        _field("blacklist", 3, _T.TYPE_MESSAGE, label=3,
               type_name=".flow.FlowFilter"),
        _field("follow", 4, _T.TYPE_BOOL),
        _field("since", 7, _T.TYPE_MESSAGE, type_name=_TS),
        _field("until", 8, _T.TYPE_MESSAGE, type_name=_TS),
        _field("first", 9, _T.TYPE_BOOL),
    ]),
    _msg("GetFlowsResponse", [
        _field("flow", 1, _T.TYPE_MESSAGE, type_name=".flow.Flow",
               oneof_index=0),
        _field("lost_events", 3, _T.TYPE_MESSAGE,
               type_name=".flow.LostEvent", oneof_index=0),
        _field("node_name", 1000, _T.TYPE_STRING),
        _field("time", 1001, _T.TYPE_MESSAGE, type_name=_TS),
    ], oneofs=["response_types"]),
    _msg("ServerStatusRequest", []),
    _msg("ServerStatusResponse", [
        _field("num_flows", 1, _T.TYPE_UINT64),
        _field("max_flows", 2, _T.TYPE_UINT64),
        _field("seen_flows", 3, _T.TYPE_UINT64),
        _field("uptime_ns", 4, _T.TYPE_UINT64),
        _field("version", 7, _T.TYPE_STRING),
        _field("flows_rate", 8, _T.TYPE_DOUBLE),
    ]),
])
_obs_fdp.service.add(name="Observer").method.add(
    name="GetFlows",
    input_type=".observer.GetFlowsRequest",
    output_type=".observer.GetFlowsResponse",
    server_streaming=True,
)
_obs_fdp.service[0].method.add(
    name="ServerStatus",
    input_type=".observer.ServerStatusRequest",
    output_type=".observer.ServerStatusResponse",
)
_pool.Add(_obs_fdp)

# ---------------------------------------------------------------------
# peer.proto (package peer)
# ---------------------------------------------------------------------
_peer_fdp = descriptor_pb2.FileDescriptorProto(
    name="peer/peer.proto", package="peer", syntax="proto3",
)
_peer_fdp.enum_type.append(_enum("ChangeNotificationType", {
    "UNKNOWN": 0, "PEER_ADDED": 1, "PEER_DELETED": 2, "PEER_UPDATED": 3,
}))
_peer_fdp.message_type.extend([
    _msg("NotifyRequest", []),
    _msg("TLS", [
        _field("enabled", 1, _T.TYPE_BOOL),
        _field("server_name", 2, _T.TYPE_STRING),
    ]),
    _msg("ChangeNotification", [
        _field("name", 1, _T.TYPE_STRING),
        _field("address", 2, _T.TYPE_STRING),
        _field("type", 3, _T.TYPE_ENUM,
               type_name=".peer.ChangeNotificationType"),
        _field("tls", 4, _T.TYPE_MESSAGE, type_name=".peer.TLS"),
    ]),
])
_peer_fdp.service.add(name="Peer").method.add(
    name="Notify",
    input_type=".peer.NotifyRequest",
    output_type=".peer.ChangeNotification",
    server_streaming=True,
)
_pool.Add(_peer_fdp)


def _cls(full_name: str):
    return message_factory.GetMessageClass(
        _pool.FindMessageTypeByName(full_name)
    )


Flow = _cls("flow.Flow")
FlowFilterPB = _cls("flow.FlowFilter")
LostEvent = _cls("flow.LostEvent")
GetFlowsRequest = _cls("observer.GetFlowsRequest")
GetFlowsResponse = _cls("observer.GetFlowsResponse")
ServerStatusRequest = _cls("observer.ServerStatusRequest")
ServerStatusResponse = _cls("observer.ServerStatusResponse")
NotifyRequest = _cls("peer.NotifyRequest")
ChangeNotification = _cls("peer.ChangeNotification")

OBSERVER_SERVICE_PB = "observer.Observer"
PEER_SERVICE_PB = "peer.Peer"

_VERDICT_NUM = {"VERDICT_UNKNOWN": 0, "FORWARDED": 1, "DROPPED": 2}
_DIR_NUM = {"TRAFFIC_DIRECTION_UNKNOWN": 0, "INGRESS": 1, "EGRESS": 2}
# CiliumEventType.type numbering follows the monitor message types the
# reference stamps (pkg/utils/flow_utils.go:102-104 trace, :292-295
# drop with sub_type = drop reason, :193-195 access-log for L7/DNS;
# numeric values per cilium pkg/monitor/api/types.go iota order, see
# sources/cilium_monitor.py). tcp_retransmit has no Cilium analog: it
# rides trace with sub_type 1 — Cilium's trace sub_types are
# observation points, which this wire does not otherwise carry, so the
# slot is free (documented divergence).
_ET_DROP, _ET_TRACE, _ET_L7 = 1, 4, 5
_ET_SUB_RETRANS = 1
_EVENT_TYPE_NUM = {"flow": _ET_TRACE, "drop": _ET_DROP,
                   "dns_request": _ET_L7, "dns_response": _ET_L7,
                   "tcp_retransmit": _ET_TRACE}
# DNS record-type names (upstream clients filter/group on these, not on
# numeric qtypes).
_QTYPE_NAMES = {1: "A", 2: "NS", 5: "CNAME", 6: "SOA", 12: "PTR",
                15: "MX", 16: "TXT", 28: "AAAA", 33: "SRV", 255: "ANY"}


def flow_dict_to_proto(f: dict[str, Any], node_name: str = "") -> Any:
    """Internal flow dict (hubble/flow.py record_to_flow) → flow.Flow."""
    msg = Flow()
    t = int(f.get("time_ns", 0))
    msg.time.seconds = t // 1_000_000_000
    msg.time.nanos = t % 1_000_000_000
    msg.verdict = _VERDICT_NUM.get(f.get("verdict", ""), 0)
    msg.traffic_direction = _DIR_NUM.get(f.get("traffic_direction", ""), 0)
    ip = f.get("ip", {})
    msg.IP.source = ip.get("source", "")
    msg.IP.destination = ip.get("destination", "")
    msg.IP.ipVersion = 1
    l4 = f.get("l4", {})
    proto = l4.get("protocol", "")
    if proto == "TCP":
        msg.l4.TCP.source_port = int(l4.get("source_port", 0))
        msg.l4.TCP.destination_port = int(l4.get("destination_port", 0))
        for name in l4.get("flags", []):
            if name in ("FIN", "SYN", "RST", "PSH", "ACK", "URG", "ECE",
                        "CWR"):
                setattr(msg.l4.TCP.flags, name, True)
    elif proto == "UDP":
        msg.l4.UDP.source_port = int(l4.get("source_port", 0))
        msg.l4.UDP.destination_port = int(l4.get("destination_port", 0))
    msg.Type = 1  # L3_L4
    # Relay-ingested flows carry their ORIGIN node; only flows born on
    # this node get stamped with the local name.
    msg.node_name = f.get("node_name") or node_name
    if f.get("drop_reason") is not None:
        msg.drop_reason = int(f["drop_reason"])
        msg.drop_reason_desc = int(f["drop_reason"])
    for side, field in (("source", msg.source), ("destination",
                                                 msg.destination)):
        ep = f.get(side) or {}
        field.namespace = ep.get("namespace", "")
        field.pod_name = ep.get("pod_name", "")
        for lbl in ep.get("labels", []):
            field.labels.append(lbl)
        for w in ep.get("workloads", []):
            if w:
                field.workloads.add(name=w)
    dns = f.get("l7_dns")
    if dns is not None:
        msg.l7.type = 1 if f.get("event_type") == "dns_request" else 2
        if dns.get("query"):
            msg.l7.dns.query = str(dns["query"])
        msg.l7.dns.rcode = int(dns.get("rcode", 0))
        qt = dns.get("qtype")
        if qt is not None:
            # Numeric qtype from the decoder; already-named qtype when a
            # relay round-trips a flow it ingested from a peer.
            if isinstance(qt, int) or str(qt).isdigit():
                msg.l7.dns.qtypes.append(_QTYPE_NAMES.get(int(qt), str(qt)))
            else:
                msg.l7.dns.qtypes.append(str(qt))
    et = f.get("event_type", "flow")
    msg.event_type.type = _EVENT_TYPE_NUM.get(et, _ET_TRACE)
    if et == "drop":
        msg.event_type.sub_type = int(f.get("drop_reason") or 0)
    elif et == "tcp_retransmit":
        msg.event_type.sub_type = _ET_SUB_RETRANS
    msg.is_reply.value = bool(f.get("is_reply", False))
    msg.reply = bool(f.get("is_reply", False))
    return msg


_VERDICT_NAME = {v: k for k, v in _VERDICT_NUM.items()}
_DIR_NAME = {v: k for k, v in _DIR_NUM.items()}


def flow_proto_to_dict(msg: Any) -> dict[str, Any]:
    """flow.Flow → internal flow dict (inverse of flow_dict_to_proto);
    the relay stores peer flows in its local FlowObserver ring this way.
    """
    f: dict[str, Any] = {
        "time_ns": msg.time.seconds * 1_000_000_000 + msg.time.nanos,
        "verdict": _VERDICT_NAME.get(msg.verdict, "VERDICT_UNKNOWN"),
        "traffic_direction": _DIR_NAME.get(
            msg.traffic_direction, "TRAFFIC_DIRECTION_UNKNOWN"
        ),
        "ip": {"source": msg.IP.source, "destination": msg.IP.destination},
        "node_name": msg.node_name,
        "is_reply": msg.is_reply.value,
    }
    which = msg.l4.WhichOneof("protocol")
    if which:
        l4msg = getattr(msg.l4, which)
        l4: dict[str, Any] = {
            "protocol": which,
            "source_port": l4msg.source_port,
            "destination_port": l4msg.destination_port,
        }
        if which == "TCP":
            l4["flags"] = [
                n for n in ("FIN", "SYN", "RST", "PSH", "ACK", "URG",
                            "ECE", "CWR")
                if getattr(l4msg.flags, n)
            ]
        f["l4"] = l4
    if msg.verdict == 2:
        f["drop_reason"] = msg.drop_reason
    for side, field in (("source", msg.source),
                        ("destination", msg.destination)):
        if field.pod_name or field.namespace:
            f[side] = {
                "namespace": field.namespace,
                "pod_name": field.pod_name,
                "labels": list(field.labels),
                "workloads": [w.name for w in field.workloads],
            }
    if msg.l7.WhichOneof("record") == "dns":
        f["l7_dns"] = {
            "query": msg.l7.dns.query,
            "rcode": msg.l7.dns.rcode,
            "qtype": list(msg.l7.dns.qtypes)[0] if msg.l7.dns.qtypes else None,
        }
        f["event_type"] = ("dns_request" if msg.l7.type == 1
                           else "dns_response")
    elif msg.event_type.type == _ET_DROP:
        f["event_type"] = "drop"
    elif (msg.event_type.type == _ET_TRACE
          and msg.event_type.sub_type == _ET_SUB_RETRANS):
        f["event_type"] = "tcp_retransmit"
        f["tcp_retransmit"] = True
    else:
        f["event_type"] = "flow"
    return f


def proto_filter_matches(filters: list, flow_msg: Any) -> bool:
    """Hubble whitelist semantics: ANY filter matches; within a filter,
    every populated field must match (any-of across repeated values)."""
    if not filters:
        return True
    for flt in filters:
        if _one_filter_matches(flt, flow_msg):
            return True
    return False


def _one_filter_matches(flt: Any, m: Any) -> bool:
    def any_prefix(vals, actual):
        return not vals or any(actual.startswith(v) for v in vals)

    if not any_prefix(list(flt.source_ip), m.IP.source):
        return False
    if not any_prefix(list(flt.destination_ip), m.IP.destination):
        return False
    if not any_prefix(list(flt.source_pod),
                      f"{m.source.namespace}/{m.source.pod_name}"):
        return False
    if not any_prefix(list(flt.destination_pod),
                      f"{m.destination.namespace}/{m.destination.pod_name}"):
        return False
    if list(flt.verdict) and m.verdict not in list(flt.verdict):
        return False
    which = m.l4.WhichOneof("protocol") or ""
    if list(flt.protocol) and which.lower() not in [
        p.lower() for p in flt.protocol
    ]:
        return False
    if list(flt.source_port) or list(flt.destination_port):
        l4 = getattr(m.l4, which) if which else None
        sp = str(getattr(l4, "source_port", "")) if l4 else ""
        dp = str(getattr(l4, "destination_port", "")) if l4 else ""
        if list(flt.source_port) and sp not in list(flt.source_port):
            return False
        if list(flt.destination_port) and dp not in list(flt.destination_port):
            return False
    return True

"""Record → flow decoding for export.

Reference analog: pkg/hubble/parser — layer34 (parser/layer34) decodes
L3/L4 + verdict/direction, seven (parser/seven) decorates DNS, and the
common decoder attaches identity from the ipcache (common/decoder.go).
Here the record already carries L3/L4; enrichment attaches pod metadata
from the cache by IP, and DNS names resolve through the host string table.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from retina_tpu.events.schema import (
    EV_DNS_REQ,
    EV_DNS_RESP,
    EV_TCP_RETRANS,
    F,
    TCP_FLAG_NAMES,
    u32_to_ip,
)

_VERDICTS = {0: "VERDICT_UNKNOWN", 1: "FORWARDED", 2: "DROPPED"}
_DIRECTIONS = {0: "TRAFFIC_DIRECTION_UNKNOWN", 1: "INGRESS", 2: "EGRESS"}
_PROTOS = {6: "TCP", 17: "UDP", 1: "ICMP"}
_EVENT_TYPES = {0: "flow", 1: "drop", 2: "dns_request", 3: "dns_response",
                4: "tcp_retransmit"}


def _endpoint_dict(ep: Any) -> dict[str, Any]:
    if ep is None:
        return {}
    return {
        "namespace": getattr(ep, "namespace", ""),
        "pod_name": getattr(ep, "name", ""),
        "labels": [f"{k}={v}" for k, v in getattr(ep, "labels", ())],
        "workloads": [getattr(ep, "workload", lambda: "")()],
    }


def record_to_flow(
    rec: np.ndarray,
    cache: Any = None,
    dns_resolver: Any = None,
) -> dict[str, Any]:
    """One (NUM_FIELDS,) record → a Hubble-flow-shaped dict."""
    meta = int(rec[F.META])
    proto = meta >> 24
    flags = (meta >> 16) & 0xFF
    src_ip = u32_to_ip(int(rec[F.SRC_IP]))
    dst_ip = u32_to_ip(int(rec[F.DST_IP]))
    ports = int(rec[F.PORTS])
    ev = int(rec[F.EVENT_TYPE])
    flow: dict[str, Any] = {
        "time_ns": (int(rec[F.TS_HI]) << 32) | int(rec[F.TS_LO]),
        "verdict": _VERDICTS.get(int(rec[F.VERDICT]), "VERDICT_UNKNOWN"),
        "ip": {"source": src_ip, "destination": dst_ip},
        "l4": {
            "protocol": _PROTOS.get(proto, str(proto)),
            "source_port": ports >> 16,
            "destination_port": ports & 0xFFFF,
        },
        "traffic_direction": _DIRECTIONS.get((meta >> 4) & 0xF,
                                             "TRAFFIC_DIRECTION_UNKNOWN"),
        "event_type": _EVENT_TYPES.get(ev, str(ev)),
        "is_reply": bool(meta & 0xF),
        "bytes": int(rec[F.BYTES]),
        "packets": int(rec[F.PACKETS]),
    }
    if proto == 6:
        flow["l4"]["flags"] = [
            name for bit, name in TCP_FLAG_NAMES.items() if flags & bit
        ]
    if int(rec[F.VERDICT]) == 2:
        flow["drop_reason"] = int(rec[F.DROP_REASON])
    if ev in (EV_DNS_REQ, EV_DNS_RESP):
        dns_col = int(rec[F.DNS])
        q: dict[str, Any] = {
            "qtype": dns_col >> 16,
            "rcode": (dns_col >> 8) & 0xFF,
        }
        if dns_resolver is not None:
            q["query"] = dns_resolver(int(rec[F.DNS_QHASH]))
        flow["l7_dns"] = q
    if ev == EV_TCP_RETRANS:
        flow["tcp_retransmit"] = True
    if cache is not None:
        flow["source"] = _endpoint_dict(cache.get_obj_by_ip(src_ip))
        flow["destination"] = _endpoint_dict(cache.get_obj_by_ip(dst_ip))
    return flow


class FlowFilter:
    """Subset of Hubble's FlowFilter: pod/namespace/verdict/protocol/
    port/ip/event_type allow-matching (any-of within a field, all-of
    across fields). ``ip`` is an EXACT match against either endpoint —
    unlike the gRPC path (proto.py _one_filter_matches), whose
    source_ip/destination_ip are independent prefix matches.
    ``event_type`` matches the flow's event_type name (flow, drop,
    dns_request, dns_response, tcp_retransmit — the `hubble observe
    --type` analog). ``since_ns``/``until_ns`` bound the flow's
    timestamp (the GetFlowsRequest since/until analog; unstamped flows
    carry time_ns 0 and fall outside any since bound)."""

    def __init__(
        self,
        pod: Optional[str] = None,
        namespace: Optional[str] = None,
        verdict: Optional[str] = None,
        protocol: Optional[str] = None,
        port: Optional[int] = None,
        ip: Optional[str] = None,
        event_type: Optional[str] = None,
        since_ns: Optional[int] = None,
        until_ns: Optional[int] = None,
    ):
        self.pod = pod
        self.namespace = namespace
        self.verdict = verdict
        self.protocol = protocol
        self.port = port
        self.ip = ip
        self.event_type = event_type
        self.since_ns = since_ns
        self.until_ns = until_ns

    def to_dict(self) -> dict[str, Any]:
        return {k: v for k, v in self.__dict__.items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "FlowFilter":
        return cls(**{
            k: d.get(k) for k in
            ("pod", "namespace", "verdict", "protocol", "port", "ip",
             "event_type", "since_ns", "until_ns")
        })

    def matches(self, flow: dict[str, Any]) -> bool:
        if self.verdict and flow.get("verdict") != self.verdict:
            return False
        if self.protocol and flow.get("l4", {}).get("protocol") != self.protocol:
            return False
        if self.port is not None:
            l4 = flow.get("l4", {})
            if self.port not in (l4.get("source_port"),
                                 l4.get("destination_port")):
                return False
        if self.pod:
            names = {flow.get("source", {}).get("pod_name"),
                     flow.get("destination", {}).get("pod_name")}
            if self.pod not in names:
                return False
        if self.namespace:
            nss = {flow.get("source", {}).get("namespace"),
                   flow.get("destination", {}).get("namespace")}
            if self.namespace not in nss:
                return False
        if self.ip:
            ips = flow.get("ip", {})
            if self.ip not in (ips.get("source"), ips.get("destination")):
                return False
        if self.event_type and flow.get("event_type") != self.event_type:
            return False
        if self.since_ns is not None or self.until_ns is not None:
            t = int(flow.get("time_ns", 0))
            if self.since_ns is not None and t < self.since_ns:
                return False
            if self.until_ns is not None and t > self.until_ns:
                return False
        return True

"""Sketch-state checkpoint/resume.

Reference analog (SURVEY.md §5.4): the reference's persistent state is
pinned BPF maps on bpffs that survive agent restarts
(pkg/bpf/setup_linux.go:19-56, retina_filter.c:20, conntrack.c:96); the
agent itself is stateless. Here the analog is the device-resident sketch
state: snapshot it to disk on shutdown (or every snapshot_interval_s) and
restore on boot, so counters/sketches survive a restart the way pinned
maps do.

Format: one .npz of the flattened pytree leaves + a config fingerprint.
The tree structure is a pure function of PipelineConfig, so leaves alone
reconstruct the state; a config mismatch (different table shapes) refuses
to load — the reference equivalent is recreating maps whose spec changed.
"""

from __future__ import annotations

import dataclasses
import json
import os

import jax
import numpy as np

from retina_tpu.log import logger
from retina_tpu.models.pipeline import PipelineConfig

_log = logger("checkpoint")


def _fingerprint(pcfg: PipelineConfig) -> str:
    return json.dumps(dataclasses.asdict(pcfg), sort_keys=True)


def save_state(path: str, state, pcfg: PipelineConfig) -> None:
    leaves = jax.tree.flatten(state)[0]
    host = [np.asarray(x) for x in leaves]
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    np.savez_compressed(
        tmp if tmp.endswith(".npz") else tmp,
        __config__=np.frombuffer(
            _fingerprint(pcfg).encode(), np.uint8
        ),
        **{f"leaf_{i}": a for i, a in enumerate(host)},
    )
    # np.savez appends .npz when missing; normalize then atomically swap.
    actual_tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"
    os.replace(actual_tmp, path)
    _log.info("state checkpoint written: %s (%d leaves)", path, len(host))


def load_state(path: str, sharded, pcfg: PipelineConfig):
    """Restore into a zero state built by ``sharded.init_state()``."""
    with np.load(path) as z:
        stored_cfg = bytes(z["__config__"]).decode()
        if stored_cfg != _fingerprint(pcfg):
            raise ValueError(
                "checkpoint config mismatch; refusing to load "
                "(table shapes changed — start fresh)"
            )
        zero = sharded.init_state()
        leaves, treedef = jax.tree.flatten(zero)
        loaded = []
        for i, leaf in enumerate(leaves):
            a = z[f"leaf_{i}"]
            if a.shape != leaf.shape or a.dtype != leaf.dtype:
                raise ValueError(
                    f"checkpoint leaf {i} shape/dtype mismatch: "
                    f"{a.shape}/{a.dtype} vs {leaf.shape}/{leaf.dtype}"
                )
            loaded.append(a)
    state = jax.tree.unflatten(treedef, loaded)
    _log.info("state checkpoint restored: %s", path)
    return state

"""kind-backed e2e harness: real cluster, real helm chart, real scrape.

Reference analog: test/e2e/retina_e2e_test.go:19-66 + framework/
scaffold — the reference creates an AKS/kind cluster, helm-installs
retina, drives scenario jobs (drop, dns, ...), and asserts Prometheus
series through the deployed agent. Here:

- the chart renders through OUR renderer (``retina-tpu deploy render``
  -> kubectl apply), proving the shipped chart + CLI path, not a
  helm-only one;
- the agent image is the repo's deploy/Dockerfile built locally and
  ``kind load``-ed (pullPolicy Never);
- scenarios reuse the SAME step DSL as the in-process e2e
  (e2e/framework.py) with cluster-backed steps;
- assertions parse the agent's real /metrics exposition fetched with
  ``kubectl exec`` (e2e/prometheus.py).

Everything shells out to kind/kubectl/docker, so this only runs where
those exist (the e2e-kind workflow; tests/test_e2e_kind.py is opt-in via
RETINA_KIND_E2E=1).
"""

from __future__ import annotations

import json
import subprocess
import tempfile
import time
from typing import Any

from retina_tpu.e2e.framework import Step, StepFailed
from retina_tpu.e2e.prometheus import parse_exposition
from retina_tpu.log import logger

_log = logger("e2e.kind")

KIND_VALUES = {
    # kind nodes have no TPU: run the agent on the CPU backend with the
    # virtual device mesh, drop the TPU scheduling constraints, and
    # capture live AF_PACKET traffic inside the node netns.
    "image.tag": "e2e",
    "image.pullPolicy": "Never",
    "agent.nodeSelector": "",
    "agent.tolerations": "",
    "agent.resources.limits": "",
    "agent.shapes.nPods": "256",
    "agent.batchCapacity": "16384",
}


def sh(*cmd: str, timeout: float = 600, check: bool = True,
       capture: bool = True) -> str:
    _log.info("$ %s", " ".join(cmd))
    res = subprocess.run(
        cmd, timeout=timeout, text=True,
        capture_output=capture,
    )
    if check and res.returncode != 0:
        raise StepFailed(
            f"command failed ({res.returncode}): {' '.join(cmd)}\n"
            f"{(res.stdout or '')[-2000:]}\n{(res.stderr or '')[-2000:]}"
        )
    return res.stdout or ""


class CreateKindCluster(Step):
    name = "create-kind-cluster"

    def __init__(self, cluster: str = "retina-tpu-e2e"):
        self.cluster = cluster

    def prevalidate(self, ctx: dict[str, Any]) -> None:
        for tool in ("kind", "kubectl", "docker"):
            sh(tool, "--help", timeout=30)

    def run(self, ctx: dict[str, Any]) -> None:
        existing = sh("kind", "get", "clusters", check=False)
        if self.cluster not in existing.split():
            sh("kind", "create", "cluster", "--name", self.cluster,
               "--wait", "120s", timeout=600)
        ctx["cluster"] = self.cluster
        ctx["kubectl"] = ("kubectl", "--context", f"kind-{self.cluster}")

    def cleanup(self, ctx: dict[str, Any]) -> None:
        if ctx.get("keep_cluster"):
            return
        sh("kind", "delete", "cluster", "--name", self.cluster,
           check=False)


class BuildAndLoadImage(Step):
    name = "build-and-load-image"

    def run(self, ctx: dict[str, Any]) -> None:
        sh("docker", "build", "-f", "deploy/Dockerfile",
           "-t", "retina-tpu:e2e", ".", timeout=1800)
        sh("kind", "load", "docker-image", "retina-tpu:e2e",
           "--name", ctx["cluster"], timeout=600)


class InstallChart(Step):
    """Render with OUR renderer, apply with kubectl (helm-free path the
    CLI ships; `helm install deploy/helm/retina-tpu` works identically
    because templates stick to the helmlite subset)."""

    name = "install-chart"

    def __init__(self, namespace: str = "retina"):
        self.namespace = namespace

    def run(self, ctx: dict[str, Any]) -> None:
        import sys

        sets = [f"{k}={v}" for k, v in KIND_VALUES.items()]
        out = sh(
            sys.executable, "-m", "retina_tpu", "deploy", "render",
            "--namespace", self.namespace,
            *[a for kv in sets for a in ("--set", kv)],
        )
        kubectl = ctx["kubectl"]
        sh(*kubectl, "create", "namespace", self.namespace, check=False)
        with tempfile.NamedTemporaryFile(
            "w", suffix=".yaml", delete=False
        ) as f:
            f.write(out)
            path = f.name
        sh(*kubectl, "apply", "-n", self.namespace, "-f", path)
        ctx["namespace"] = self.namespace

    def cleanup(self, ctx: dict[str, Any]) -> None:
        kubectl = ctx.get("kubectl")
        if kubectl and not ctx.get("keep_cluster"):
            sh(*kubectl, "delete", "namespace", self.namespace,
               check=False, timeout=180)


class WaitAgentReady(Step):
    name = "wait-agent-ready"

    def __init__(self, timeout_s: float = 420.0):
        self.timeout_s = timeout_s

    def run(self, ctx: dict[str, Any]) -> None:
        kubectl, ns = ctx["kubectl"], ctx["namespace"]
        sh(*kubectl, "-n", ns, "rollout", "status",
           "daemonset/retina-tpu-agent",
           f"--timeout={int(self.timeout_s)}s",
           timeout=self.timeout_s + 30)
        pods = json.loads(sh(
            *kubectl, "-n", ns, "get", "pods", "-l",
            "app=retina-tpu-agent", "-o", "json",
        ))
        names = [p["metadata"]["name"] for p in pods["items"]]
        if not names:
            raise StepFailed("no agent pods scheduled")
        ctx["agent_pod"] = names[0]


class GenerateClusterTraffic(Step):
    """Drive the drop + dns scenarios with REAL cluster traffic: DNS
    lookups resolve through kube-dns (the dns scenario) and connects to
    a port nothing listens on produce failed/denied flows (the drop
    scenario's traffic shape, scenario.go:19-60)."""

    name = "generate-traffic"

    def run(self, ctx: dict[str, Any]) -> None:
        kubectl, ns = ctx["kubectl"], ctx["namespace"]
        script = (
            "for i in $(seq 1 40); do "
            "nslookup kubernetes.default.svc.cluster.local >/dev/null 2>&1; "
            "wget -q -T 1 -O- http://10.96.255.254:9/ >/dev/null 2>&1; "
            "done; echo traffic-done"
        )
        out = sh(
            *kubectl, "-n", ns, "run", "trafficgen", "--rm", "-i",
            "--restart=Never", "--image=busybox:1.36", "--", "sh", "-c",
            script, timeout=300,
        )
        if "traffic-done" not in out:
            raise StepFailed(f"traffic generator failed: {out[-500:]}")

    def cleanup(self, ctx: dict[str, Any]) -> None:
        kubectl, ns = ctx.get("kubectl"), ctx.get("namespace")
        if kubectl:
            sh(*kubectl, "-n", ns, "delete", "pod", "trafficgen",
               check=False)


class ScrapeDeployedAgent(Step):
    """Fetch /metrics from inside the agent pod and parse the
    exposition; retries until the expected families appear (publish
    cadence + first-window lag)."""

    name = "scrape-deployed-agent"

    def __init__(self, required: tuple[str, ...] = (), timeout_s: float = 120.0):
        self.required = required
        self.timeout_s = timeout_s

    def run(self, ctx: dict[str, Any]) -> None:
        kubectl, ns = ctx["kubectl"], ctx["namespace"]
        pod = ctx["agent_pod"]
        deadline = time.monotonic() + self.timeout_s
        last = ""
        while time.monotonic() < deadline:
            last = sh(
                *kubectl, "-n", ns, "exec", pod, "--",
                "python", "-c",
                "import urllib.request;"
                "print(urllib.request.urlopen("
                "'http://127.0.0.1:10093/metrics').read().decode())",
                check=False, timeout=60,
            )
            samples = parse_exposition(last)
            fams = {s.name for s in samples}
            if all(any(r in f for f in fams) for r in self.required):
                ctx["samples"] = samples
                return
            time.sleep(5)
        raise StepFailed(
            f"required families {self.required} not found; got "
            f"{sorted({s.name for s in parse_exposition(last)})[:40]}"
        )

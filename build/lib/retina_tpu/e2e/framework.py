"""Runner/Job/Step: the scenario execution DSL.

Reference analog: test/e2e/framework/types/runner.go:11-40 (Runner wraps a
Job, Run() + t-failure propagation), job.go:23-45 (ordered steps, values
map, fail-fast, deferred cleanup steps run even on failure), step.go
(Step interface: Prevalidate/Run/Stop).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

from retina_tpu.log import logger


class StepFailed(AssertionError):
    """A step's contract was not met (scenario assertion failure)."""


class Step:
    """One typed scenario action. Subclasses set ``name`` and implement
    ``run(ctx)``; ``cleanup(ctx)`` (optional) runs in reverse order even
    when an earlier step failed — the job.go deferred-cleanup semantics.
    """

    name = "step"

    def prevalidate(self, ctx: dict[str, Any]) -> None:  # noqa: B027
        """Cheap static checks before anything runs (step.go Prevalidate)."""

    def run(self, ctx: dict[str, Any]) -> None:
        raise NotImplementedError

    def cleanup(self, ctx: dict[str, Any]) -> None:  # noqa: B027
        """Reverse-order teardown; must be idempotent and never raise."""


@dataclasses.dataclass
class Job:
    """Ordered steps sharing a ctx values dict (job.go Values)."""

    name: str
    steps: list[Step] = dataclasses.field(default_factory=list)

    def add(self, *steps: Step) -> "Job":
        self.steps.extend(steps)
        return self

    def run(self) -> dict[str, Any]:
        log = logger("e2e")
        ctx: dict[str, Any] = {"job": self.name}
        for s in self.steps:
            s.prevalidate(ctx)
        started: list[Step] = []
        t_job = time.perf_counter()
        try:
            for s in self.steps:
                t0 = time.perf_counter()
                log.info("[%s] step %s ...", self.name, s.name)
                started.append(s)
                s.run(ctx)
                log.info(
                    "[%s] step %s ok (%.2fs)",
                    self.name, s.name, time.perf_counter() - t0,
                )
            return ctx
        finally:
            for s in reversed(started):
                try:
                    s.cleanup(ctx)
                except Exception:  # noqa: BLE001 — cleanup never raises
                    log.exception("[%s] cleanup of %s failed", self.name, s.name)
            log.info(
                "[%s] done in %.2fs", self.name, time.perf_counter() - t_job
            )


class Runner:
    """Runs a Job and converts failures into test failures (runner.go)."""

    def __init__(self, job: Job):
        self.job = job

    def run(self) -> dict[str, Any]:
        return self.job.run()

"""Prometheus exposition parsing + retrying metric assertions.

Reference analog: test/e2e/framework/prometheus/prometheus.go:25-50 —
CheckMetric scrapes the endpoint, parses the exposition format, matches a
metric name + label subset, and retries with backoff until the deadline
(metrics lag traffic, so one-shot checks race the pipeline).
"""

from __future__ import annotations

import dataclasses
import time
import urllib.request
from typing import Callable, Iterable

from retina_tpu.e2e.framework import StepFailed


@dataclasses.dataclass
class Sample:
    name: str
    labels: dict[str, str]
    value: float


def parse_exposition(text: str) -> list[Sample]:
    """Minimal exposition-format parser (families + label sets + values).

    Handles the subset the exporter emits: `name{l1="v1",...} value` and
    bare `name value` lines; HELP/TYPE comments skipped.
    """
    out: list[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, _, val = line.rpartition(" ")
            if "{" in metric:
                name, _, rest = metric.partition("{")
                rest = rest.rstrip("}")
                labels: dict[str, str] = {}
                # label values may contain escaped quotes; the exporter
                # never emits them, so a simple split is exact here.
                for part in filter(None, rest.split('",')):
                    k, _, v = part.partition('="')
                    labels[k.strip().lstrip(",")] = v.rstrip('"')
            else:
                name, labels = metric, {}
            out.append(Sample(name=name.strip(), labels=labels,
                              value=float(val)))
        except ValueError:
            continue
    return out


class PrometheusChecker:
    """Scrape-and-assert with retry against a live /metrics endpoint."""

    def __init__(self, url: str, timeout_s: float = 30.0,
                 interval_s: float = 0.25):
        self.url = url
        self.timeout_s = timeout_s
        self.interval_s = interval_s

    def scrape(self) -> list[Sample]:
        text = urllib.request.urlopen(self.url, timeout=5).read().decode()
        return parse_exposition(text)

    @staticmethod
    def _match(samples: Iterable[Sample], name: str,
               labels: dict[str, str] | None) -> list[Sample]:
        labels = labels or {}
        return [
            s for s in samples
            if s.name == name
            and all(s.labels.get(k) == v for k, v in labels.items())
        ]

    def check_metric(
        self,
        name: str,
        labels: dict[str, str] | None = None,
        value: Callable[[float], bool] | float | None = None,
    ) -> Sample:
        """Wait until a sample with ``name`` + label subset (+ value
        predicate) appears; return it. Raises StepFailed at deadline with
        the closest near-misses for diagnosis (prometheus.go's retry +
        verbose mismatch logging)."""
        if value is None:
            pred = lambda v: True
        elif callable(value):
            pred = value
        else:
            pred = lambda v, want=float(value): v == want
        deadline = time.monotonic() + self.timeout_s
        last: list[Sample] = []
        while time.monotonic() < deadline:
            try:
                samples = self.scrape()
            except Exception:
                time.sleep(self.interval_s)
                continue
            hits = self._match(samples, name, labels)
            for h in hits:
                if pred(h.value):
                    return h
            last = hits or [s for s in samples if s.name == name][:5]
            time.sleep(self.interval_s)
        raise StepFailed(
            f"metric {name}{labels or {}} with required value not found "
            f"within {self.timeout_s}s; closest: "
            + "; ".join(f"{s.labels}={s.value}" for s in last[:5])
        )

    def sum_metric(self, name: str,
                   labels: dict[str, str] | None = None) -> float:
        """Sum of all currently-matching samples (0.0 if none)."""
        try:
            return sum(s.value for s in self._match(self.scrape(), name, labels))
        except Exception:
            return 0.0

"""Capture providers: who actually writes the pcap.

Reference analog: pkg/capture/provider/network_capture_unix.go (383 LoC) —
wraps tcpdump with duration/size limits; Windows netsh variant
(network_capture_win.go). Three providers here, best-available order:

1. TcpdumpProvider — subprocess tcpdump (same flags the reference uses),
   when the binary exists.
2. SocketProvider — in-process AF_PACKET raw capture with a pure-Python
   BPF-less filter (host/port matching on decoded headers); needs root.
3. ReplayProvider — captures from the agent's own record stream by
   snapshotting sink blocks into a synthesized pcap. Always available (the
   TPU framework's event sources may be virtual, where tcpdump has nothing
   to see).
"""

from __future__ import annotations

import shutil
import subprocess
import threading
import time

import numpy as np

from retina_tpu.events.schema import F, u32_to_ip
from retina_tpu.log import logger

_log = logger("capture.provider")


class CaptureError(RuntimeError):
    pass


class TcpdumpProvider:
    """tcpdump wrapper (network_capture_unix.go CaptureNetworkPacket)."""

    name = "tcpdump"

    @staticmethod
    def available() -> bool:
        return shutil.which("tcpdump") is not None

    def capture(
        self,
        out_path: str,
        filter_expr: str = "",
        iface: str = "any",
        duration_s: int = 60,
        max_size_mb: int = 100,
        packet_size: int = 0,
    ) -> None:
        cmd = ["tcpdump", "-i", iface, "-w", out_path, "-W", "1",
               "-G", str(duration_s)]
        if packet_size:
            cmd += ["-s", str(packet_size)]
        if max_size_mb:
            cmd += ["-C", str(max_size_mb)]
        if filter_expr:
            cmd.append(filter_expr)
        try:
            subprocess.run(
                cmd, timeout=duration_s + 30, check=True, capture_output=True
            )
        except FileNotFoundError as e:
            raise CaptureError("tcpdump not installed") from e
        except subprocess.CalledProcessError as e:
            raise CaptureError(
                f"tcpdump failed: {e.stderr.decode(errors='replace')[:300]}"
            ) from e
        except subprocess.TimeoutExpired as e:
            raise CaptureError("tcpdump did not terminate") from e


def netsh_filter_from_ips(ips: list[str]) -> str:
    """Pod IPs → netsh capture filter (crd_to_job.go:501-538
    getNetshFilterWithPodIPAddress): netsh takes address groups per
    family, e.g. ``IPv4.Address=(10.0.0.1,10.0.0.2)``."""
    v4 = [ip for ip in ips if ip and ":" not in ip]
    v6 = [ip for ip in ips if ip and ":" in ip]
    groups = []
    if v4:
        groups.append(f"IPv4.Address=({','.join(v4)})")
    if v6:
        groups.append(f"IPv6.Address=({','.join(v6)})")
    return " ".join(groups)


def tcpdump_filter_to_netsh(filter_expr: str) -> str:
    """tcpdump filter (what the translator synthesizes for every node)
    → netsh address groups. netsh has no tcpdump syntax: only the
    ``host <ip>`` terms survive (per-family address groups); port and
    protocol terms have no netsh capture-filter equivalent and are
    dropped — the reference similarly filters Windows captures by pod
    IP only (crd_to_job.go:448 netshFilter from PodIpAddresses)."""
    tokens = filter_expr.replace("(", " ").replace(")", " ").split()
    ips = [tokens[i + 1] for i, t in enumerate(tokens[:-1])
           if t == "host"]
    return netsh_filter_from_ips(ips)


class NetshProvider:
    """Windows ``netsh trace`` wrapper
    (network_capture_win.go:63-150): stop any stale trace session,
    ``netsh trace start capture=yes`` into the .etl file with an
    optional address filter and maxSize, sleep the duration, ``netsh
    trace stop``. The command runner is injectable so the control flow
    is testable off-Windows; only availability is win32-gated."""

    name = "netsh"
    suffix = ".etl"  # manager names the capture file with this

    def __init__(self, runner=None, sleep=time.sleep):
        self._run = runner or self._default_runner
        self._sleep = sleep
        self._log = logger("capture.netsh")

    @staticmethod
    def _default_runner(args: list[str], timeout: float):
        return subprocess.run(["cmd", "/C"] + args, capture_output=True,
                              text=True, timeout=timeout)

    def _cmd(self, args: list[str], timeout: float):
        """Runner wrapped into the CaptureError contract the other
        providers keep (providers.py TcpdumpProvider)."""
        try:
            return self._run(args, timeout)
        except FileNotFoundError as e:
            raise CaptureError("netsh/cmd not available") from e
        except subprocess.TimeoutExpired as e:
            raise CaptureError(
                f"netsh did not terminate: {' '.join(args)}"
            ) from e

    @staticmethod
    def available() -> bool:
        import sys

        return sys.platform == "win32" and shutil.which("netsh") is not None

    @staticmethod
    def _err(res) -> str:
        return ((res.stderr or "") + (res.stdout or ""))[:300]

    def _session_running(self) -> bool:
        # `netsh trace show status` exits 1 when no session runs
        # (network_capture_win.go:153-165).
        res = self._cmd(["netsh", "trace", "show", "status"], 30)
        return res.returncode == 0

    def capture(
        self,
        out_path: str,
        filter_expr: str = "",
        iface: str = "any",  # netsh traces all interfaces
        duration_s: int = 60,
        max_size_mb: int = 100,
        packet_size: int = 0,
    ) -> None:
        if self._session_running():
            self._log.info("stopping stale netsh trace session")
            self._cmd(["netsh", "trace", "stop"], 120)
        args = ["netsh", "trace", "start", "capture=yes",
                "report=disabled", "overwrite=yes",
                f"tracefile={out_path}"]
        netsh_filter = tcpdump_filter_to_netsh(filter_expr)
        if filter_expr and not netsh_filter:
            self._log.warning(
                "filter %r has no netsh equivalent; capturing unfiltered",
                filter_expr,
            )
        if netsh_filter:
            # Address groups are separate argv entries
            # (network_capture_win.go:86-93).
            args += netsh_filter.split(" ")
        if max_size_mb:
            args.append(f"maxSize={max_size_mb}")
        res = self._cmd(args, 60)
        if res.returncode != 0:
            raise CaptureError(
                f"netsh trace start failed: {self._err(res)}"
            )
        try:
            self._sleep(duration_s)
        finally:
            stop = self._cmd(["netsh", "trace", "stop"], 300)
            if stop.returncode != 0:
                raise CaptureError(
                    f"netsh trace stop failed: {self._err(stop)}"
                )


class SocketProvider:
    """AF_PACKET raw-socket capture (root)."""

    name = "socket"

    @staticmethod
    def available() -> bool:
        import socket

        if not hasattr(socket, "AF_PACKET"):
            return False
        try:
            s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW,
                              socket.htons(3))
            s.close()
            return True
        except (PermissionError, OSError):
            return False

    def capture(
        self,
        out_path: str,
        filter_expr: str = "",
        iface: str = "",
        duration_s: int = 60,
        max_size_mb: int = 100,
        packet_size: int = 0,
    ) -> None:
        import socket
        import struct

        s = socket.socket(socket.AF_PACKET, socket.SOCK_RAW, socket.htons(3))
        if iface and iface != "any":
            s.bind((iface, 0))
        s.settimeout(0.2)
        deadline = time.monotonic() + duration_s
        max_bytes = max_size_mb * 1024 * 1024
        written = 0
        with open(out_path, "wb") as fh:
            fh.write(struct.pack("<IHHiIII", 0xA1B23C4D, 2, 4, 0, 0,
                                 65535, 1))
            while time.monotonic() < deadline and written < max_bytes:
                try:
                    frame = s.recv(65535)
                except (TimeoutError, socket.timeout):
                    continue
                if packet_size:
                    frame = frame[:packet_size]
                now = time.time_ns()
                fh.write(struct.pack("<IIII", now // 10**9, now % 10**9,
                                     len(frame), len(frame)))
                fh.write(frame)
                written += 16 + len(frame)
        s.close()


class ReplayProvider:
    """Capture the agent's own event stream into a pcap.

    The TPU-native framework's packets may never touch this host's NICs
    (pcap replay, external feeds) — the faithful "capture" is a window of
    the record stream itself, re-encoded as packets. Needs a live engine
    to observe; otherwise synthesizes from the configured source.
    """

    name = "replay"

    def __init__(self, engine=None, source=None):
        self._engine = engine
        self._source = source

    @staticmethod
    def available() -> bool:
        return True

    def capture(
        self,
        out_path: str,
        filter_expr: str = "",
        iface: str = "",
        duration_s: int = 60,
        max_size_mb: int = 100,
        packet_size: int = 0,
    ) -> None:
        from retina_tpu.sources.pcapdecode import synthesize_pcap

        records: list[np.ndarray] = []
        max_events = max_size_mb * 1024 * 1024 // 80
        if self._engine is not None:
            done = threading.Event()
            lock = threading.Lock()

            def obs(rec: np.ndarray, plugin: str) -> None:
                with lock:
                    if sum(len(r) for r in records) < max_events:
                        records.append(rec.copy())
                    else:
                        done.set()

            self._engine.add_observer(obs)
            done.wait(duration_s)
            # NOTE: engine observers are append-only by design (the
            # reference's monitor-agent consumers are too); the observer
            # becomes inert after capture.
            self._stop_obs = obs
        elif self._source is not None:
            t_end = time.monotonic() + min(duration_s, 5)
            while time.monotonic() < t_end and \
                    sum(len(r) for r in records) < max_events:
                records.append(self._source())
        if not records:
            raise CaptureError("no events observed during capture window")
        rec = np.concatenate(records)[:max_events]
        pkts = [
            dict(
                src_ip=int(r[F.SRC_IP]), dst_ip=int(r[F.DST_IP]),
                sport=int(r[F.PORTS]) >> 16, dport=int(r[F.PORTS]) & 0xFFFF,
                proto=int(r[F.META]) >> 24,
                tcp_flags=(int(r[F.META]) >> 16) & 0xFF,
                ts_ns=(int(r[F.TS_HI]) << 32) | int(r[F.TS_LO]),
                tsval=int(r[F.TSVAL]), tsecr=int(r[F.TSECR]),
            )
            for r in rec
        ]
        if filter_expr:
            pkts = _apply_filter(pkts, filter_expr)
        with open(out_path, "wb") as fh:
            fh.write(synthesize_pcap(pkts))


def _apply_filter(pkts: list[dict], expr: str) -> list[dict]:
    """Minimal host/port filter evaluation for replay captures (the
    synthesized expressions from translator.synthesize_filter)."""
    import re

    hosts = set(re.findall(r"host (\d+\.\d+\.\d+\.\d+)", expr))
    ports = {int(p) for p in re.findall(r"port (\d+)", expr)}

    def keep(p: dict) -> bool:
        ok = True
        if hosts:
            ok &= (u32_to_ip(p["src_ip"]) in hosts
                   or u32_to_ip(p["dst_ip"]) in hosts)
        if ports:
            ok &= p["sport"] in ports or p["dport"] in ports
        return ok

    return [p for p in pkts if keep(p)]


def best_provider(engine=None, source=None):
    """Best-available provider (the reference picks tcpdump vs netsh by
    OS; we pick by capability)."""
    if TcpdumpProvider.available():
        return TcpdumpProvider()
    if NetshProvider.available():
        return NetshProvider()
    if SocketProvider.available():
        return SocketProvider()
    return ReplayProvider(engine=engine, source=source)

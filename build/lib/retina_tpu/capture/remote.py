"""Remote capture artifact stores over plain REST — no cloud SDKs.

Reference analog: pkg/capture/outputlocation/blob.go + s3.go upload via
the Azure/AWS SDKs, and cli/cmd/capture/download.go lists+downloads from
blob storage with the storage SDK. This environment ships neither SDK,
and neither is needed: a capture artifact lifecycle is four verbs
(list/upload/download/delete) over HTTP —

- :class:`BlobStore`: Azure Blob REST against a container SAS URL
  (x-ms-blob-type PUT, restype=container&comp=list, bare GET/DELETE).
  The SAS query string IS the credential, exactly like the reference's
  ``BLOB_URL`` env contract (download.go:19).
- :class:`S3Store`: S3 REST with SigV4 request signing from the standard
  AWS env credentials (AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY,
  optional AWS_SESSION_TOKEN), endpoint-overridable for S3-compatible
  stores and tests.

Both are exercised in tests against a local fake HTTP server
(tests/test_capture_remote.py), so the upload/download/delete paths that
were dead code behind missing SDKs are now first-class tested code.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import hmac
import os
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from retina_tpu.log import logger

_log = logger("capture.remote")


@dataclasses.dataclass
class RemoteArtifact:
    name: str
    size: int
    last_modified: str


class RemoteStoreError(RuntimeError):
    pass


def _request(
    req: urllib.request.Request,
    timeout: float = 60.0,
    stream_to: str | None = None,
) -> bytes:
    """Run one request; with ``stream_to`` the body is streamed to that
    file path in chunks (capture tarballs can exceed the capture pod's
    memory limit — never buffer them whole)."""
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if stream_to is None:
                return resp.read()
            import shutil

            with open(stream_to, "wb") as fh:
                shutil.copyfileobj(resp, fh, length=1 << 20)
            return b""
    except urllib.error.HTTPError as e:
        detail = e.read()[:300].decode(errors="replace")
        raise RemoteStoreError(
            f"{req.get_method()} {req.full_url.split('?')[0]}: "
            f"HTTP {e.code} {detail}"
        ) from e
    except urllib.error.URLError as e:
        raise RemoteStoreError(f"{req.full_url.split('?')[0]}: {e}") from e


def _strip_ns(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


# ---------------------------------------------------------------------------
# Azure Blob over a container SAS URL


class BlobStore:
    """Container-level SAS URL client (the BLOB_URL contract)."""

    def __init__(self, sas_url: str):
        u = urllib.parse.urlsplit(sas_url)
        if not u.scheme or not u.netloc or not u.path.strip("/"):
            raise ValueError(
                "blob SAS URL must be https://<account>/<container>?<sas>"
            )
        self.base = f"{u.scheme}://{u.netloc}{u.path.rstrip('/')}"
        self.sas = u.query

    def _url(self, name: str = "", params: str = "") -> str:
        path = f"{self.base}/{urllib.parse.quote(name)}" if name else self.base
        qs = "&".join(p for p in (params, self.sas) if p)
        return f"{path}?{qs}" if qs else path

    def list(self, prefix: str = "") -> list[RemoteArtifact]:
        out: list[RemoteArtifact] = []
        marker = ""
        while True:
            params = "restype=container&comp=list"
            if prefix:
                params += f"&prefix={urllib.parse.quote(prefix, safe='')}"
            if marker:
                params += f"&marker={urllib.parse.quote(marker, safe='')}"
            body = _request(urllib.request.Request(self._url(params=params)))
            root = ET.fromstring(body)
            for blob in root.iter():
                if _strip_ns(blob.tag) != "Blob":
                    continue
                fields = {_strip_ns(c.tag): c for c in blob}
                props = {
                    _strip_ns(c.tag): (c.text or "")
                    for c in fields.get("Properties", [])
                }
                out.append(RemoteArtifact(
                    name=fields["Name"].text or "",
                    size=int(props.get("Content-Length", 0) or 0),
                    last_modified=props.get("Last-Modified", ""),
                ))
            # Pagination: a non-empty NextMarker means more pages
            # (5000-blob page cap on real Azure).
            marker = ""
            for el in root.iter():
                if _strip_ns(el.tag) == "NextMarker":
                    marker = el.text or ""
            if not marker:
                return out

    def upload(self, name: str, src_path: str) -> str:
        size = os.path.getsize(src_path)
        with open(src_path, "rb") as fh:
            req = urllib.request.Request(
                self._url(name), data=fh, method="PUT",
                headers={"x-ms-blob-type": "BlockBlob",
                         "Content-Type": "application/octet-stream",
                         "Content-Length": str(size)},
            )
            _request(req)
        return f"{self.base}/{name}"

    def download(self, name: str, dst_path: str) -> str:
        _request(urllib.request.Request(self._url(name)), stream_to=dst_path)
        return dst_path

    def delete(self, name: str) -> None:
        _request(urllib.request.Request(self._url(name), method="DELETE"))


# ---------------------------------------------------------------------------
# S3 with SigV4


class S3Store:
    """Minimal SigV4 S3 client (PutObject/GetObject/DeleteObject/ListV2)."""

    def __init__(
        self,
        bucket: str,
        region: str = "us-east-1",
        endpoint: str = "",
        access_key: str | None = None,
        secret_key: str | None = None,
        session_token: str | None = None,
    ):
        self.bucket = bucket
        self.region = region or "us-east-1"
        self.endpoint = (
            endpoint.rstrip("/")
            or f"https://{bucket}.s3.{self.region}.amazonaws.com"
        )
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = (
            secret_key or os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        )
        self.session_token = (
            session_token or os.environ.get("AWS_SESSION_TOKEN", "")
        )

    def credentialed(self) -> bool:
        return bool(self.access_key and self.secret_key)

    # -- SigV4 (AWS General Reference, "Signature Version 4") ---------
    def _sign(
        self, method: str, enc_path: str, query_pairs: list[tuple[str, str]],
        payload_hash: str, now: datetime.datetime,
    ) -> dict[str, str]:
        """``enc_path`` is the percent-encoded path EXACTLY as sent (the
        canonical URI is that encoding, not a re-encoding of it); query
        values canonicalize with '/' escaped (quote safe='')."""
        host = urllib.parse.urlsplit(self.endpoint).netloc
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = {
            "host": host,
            "x-amz-content-sha256": payload_hash,
            "x-amz-date": amz_date,
        }
        if self.session_token:
            headers["x-amz-security-token"] = self.session_token
        signed = ";".join(sorted(headers))
        canonical_query = "&".join(
            f"{urllib.parse.quote(k, safe='')}="
            f"{urllib.parse.quote(v, safe='')}"
            for k, v in sorted(query_pairs)
        )
        canonical = "\n".join([
            method,
            enc_path,
            canonical_query,
            "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
            signed,
            payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ])

        def h(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = h(h(h(h(b"AWS4" + self.secret_key.encode(), datestamp),
                  self.region), "s3"), "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}"
        )
        headers.pop("host")  # urllib sets it; signing included it
        return headers

    _UNSIGNED = "UNSIGNED-PAYLOAD"

    def _call(
        self, method: str, key: str = "",
        query_pairs: list[tuple[str, str]] | None = None,
        data=None, content_length: int | None = None,
        stream_to: str | None = None,
    ) -> bytes:
        query_pairs = query_pairs or []
        enc_path = "/" + urllib.parse.quote(key, safe="/")
        # Streaming bodies hash as UNSIGNED-PAYLOAD (standard SigV4
        # option over HTTPS) so a multi-hundred-MB tarball never has to
        # be buffered just to compute its digest.
        if data is None:
            payload_hash = hashlib.sha256(b"").hexdigest()
        else:
            payload_hash = self._UNSIGNED
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = self._sign(method, enc_path, query_pairs, payload_hash, now)
        if content_length is not None:
            headers["Content-Length"] = str(content_length)
        # Same percent-encoding as the canonical query in _sign (space ->
        # %20, never '+'): SigV4 servers recompute the canonical string
        # from the bytes on the wire, so urlencode's quote_plus would
        # break the signature for any key/prefix/token with a space.
        qs = "&".join(
            f"{urllib.parse.quote(k, safe='')}="
            f"{urllib.parse.quote(v, safe='')}"
            for k, v in query_pairs
        )
        url = f"{self.endpoint}{enc_path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(
            url, data=data, method=method, headers=headers
        )
        return _request(req, stream_to=stream_to)

    def list(self, prefix: str = "") -> list[RemoteArtifact]:
        out: list[RemoteArtifact] = []
        token = ""
        while True:
            pairs = [("list-type", "2")]
            if prefix:
                pairs.append(("prefix", prefix))
            if token:
                pairs.append(("continuation-token", token))
            root = ET.fromstring(self._call("GET", query_pairs=pairs))
            for item in root.iter():
                if _strip_ns(item.tag) != "Contents":
                    continue
                fields = {_strip_ns(c.tag): (c.text or "") for c in item}
                out.append(RemoteArtifact(
                    name=fields.get("Key", ""),
                    size=int(fields.get("Size", 0) or 0),
                    last_modified=fields.get("LastModified", ""),
                ))
            # ListObjectsV2 pages at 1000 keys.
            token = ""
            for el in root.iter():
                if _strip_ns(el.tag) == "NextContinuationToken":
                    token = el.text or ""
            if not token:
                return out

    def upload(self, key: str, src_path: str) -> str:
        size = os.path.getsize(src_path)
        with open(src_path, "rb") as fh:
            self._call("PUT", key=key, data=fh, content_length=size)
        return f"s3://{self.bucket}/{key}"

    def download(self, key: str, dst_path: str) -> str:
        self._call("GET", key=key, stream_to=dst_path)
        return dst_path

    def delete(self, key: str) -> None:
        self._call("DELETE", key=key)

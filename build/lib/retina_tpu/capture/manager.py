"""Node-side capture manager.

Reference analog: pkg/capture/capture_manager.go:29-120 — the binary run
inside each capture Job: set up the provider, capture packets, collect
network metadata (ip/iptables/conntrack dumps, :73-77), tar.gz everything,
and ship it to every enabled output location. The same flow here, executed
by the operator's local job runner (retina_tpu/operator) or directly by
the CLI.
"""

from __future__ import annotations

import datetime
import os
import subprocess
import tarfile
import tempfile

from retina_tpu.capture.outputs import outputs_from_spec
from retina_tpu.capture.providers import best_provider
from retina_tpu.capture.translator import CaptureJob
from retina_tpu.log import logger

_log = logger("capture.manager")

# Metadata commands (capture_manager.go CollectMetadata :73-77); each is
# best-effort — absent tools just produce an error note in the file.
_METADATA_CMDS = {
    "ip-addr.txt": ["ip", "addr"],
    "ip-route.txt": ["ip", "route"],
    "iptables.txt": ["iptables-save"],
    "proc-net-dev.txt": ["cat", "/proc/net/dev"],
    "proc-net-tcp.txt": ["cat", "/proc/net/tcp"],
    "conntrack.txt": ["conntrack", "-L"],
}


class CaptureManager:
    def __init__(self, provider=None):
        self._provider = provider

    def capture_network(self, job: CaptureJob, work_dir: str) -> str:
        """Run the packet capture; returns the capture-file path."""
        provider = self._provider or best_provider()
        stamp = datetime.datetime.now().strftime("%Y%m%d%H%M%S")
        # Providers own their file format: .pcap for tcpdump/socket/
        # replay, .etl for netsh (the path returned IS the file written).
        suffix = getattr(provider, "suffix", ".pcap")
        pcap = os.path.join(
            work_dir, f"{job.job_name()}-{stamp}{suffix}"
        )
        _log.info(
            "capturing on %s: provider=%s filter=%r duration=%ds",
            job.node_name, provider.name, job.filter_expr, job.duration_s,
        )
        provider.capture(
            pcap,
            filter_expr=job.filter_expr,
            duration_s=job.duration_s,
            max_size_mb=job.max_size_mb,
            packet_size=job.packet_size_bytes,
        )
        return pcap

    def collect_metadata(self, work_dir: str) -> list[str]:
        """Network state dumps (CollectMetadata analog)."""
        meta_dir = os.path.join(work_dir, "metadata")
        os.makedirs(meta_dir, exist_ok=True)
        written = []
        for fname, cmd in _METADATA_CMDS.items():
            path = os.path.join(meta_dir, fname)
            try:
                out = subprocess.run(
                    cmd, capture_output=True, timeout=10
                ).stdout
            except (OSError, subprocess.TimeoutExpired) as e:
                out = f"unavailable: {e}".encode()
            with open(path, "wb") as fh:
                fh.write(out)
            written.append(path)
        return written

    def run_job(self, job: CaptureJob) -> list[str]:
        """Full node-side flow: capture → metadata → tarball → outputs.
        Returns artifact paths/URLs."""
        with tempfile.TemporaryDirectory(prefix="retina-capture-") as wd:
            pcap = self.capture_network(job, wd)
            if job.include_metadata:
                self.collect_metadata(wd)
            tarball = os.path.join(
                wd, os.path.splitext(os.path.basename(pcap))[0]
                + ".tar.gz"
            )
            with tarfile.open(tarball, "w:gz") as tf:
                tf.add(pcap, arcname=os.path.basename(pcap))
                meta_dir = os.path.join(wd, "metadata")
                if os.path.isdir(meta_dir):
                    tf.add(meta_dir, arcname="metadata")
            sinks = outputs_from_spec(job.output)
            if not sinks:
                raise RuntimeError("no enabled output location")
            return [s.output(tarball) for s in sinks]

"""Distributed packet-capture subsystem (reference pkg/capture).

- translator: Capture spec → per-node capture jobs + tcpdump filter
  synthesis (crd_to_job.go).
- manager: node-side capture execution + metadata + tarball
  (capture_manager.go).
- providers: tcpdump subprocess / AF_PACKET socket / event-stream replay
  (provider/network_capture_unix.go).
- outputs: hostPath / PVC-path / blob / S3 sinks (outputlocation/).
"""

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.translator import (
    CaptureJob,
    synthesize_filter,
    translate_capture_to_jobs,
)

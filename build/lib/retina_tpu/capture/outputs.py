"""Capture output locations.

Reference analog: pkg/capture/outputlocation/ — hostPath (hostpath.go),
PVC (pvc.go), Azure blob SAS upload (blob.go), S3 (s3.go). Every location
implements {Name, Enabled, Output(srcFile)}. Blob/S3 speak the storage
REST APIs directly (capture/remote.py) instead of requiring cloud SDKs,
so Enabled() depends only on configuration (SAS URL present; bucket +
AWS env credentials present) — and the upload paths run under test
against a fake storage server (tests/test_capture_remote.py).
"""

from __future__ import annotations

import os
import shutil

from retina_tpu.log import logger

_log = logger("capture.output")


class HostPathOutput:
    """outputlocation/hostpath.go."""

    name = "hostpath"

    def __init__(self, path: str):
        self.path = path

    def enabled(self) -> bool:
        return bool(self.path)

    def output(self, src_file: str) -> str:
        os.makedirs(self.path, exist_ok=True)
        dst = os.path.join(self.path, os.path.basename(src_file))
        shutil.copy2(src_file, dst)
        _log.info("capture artifact: %s", dst)
        return dst


class PvcOutput(HostPathOutput):
    """outputlocation/pvc.go — a PVC is a mounted path node-side; the
    operator resolves the claim to its mount point."""

    name = "pvc"

    def __init__(self, claim: str, mount_root: str = "/mnt"):
        super().__init__(os.path.join(mount_root, claim) if claim else "")
        self.claim = claim


class BlobOutput:
    """outputlocation/blob.go — Azure blob container-SAS upload, spoken
    as plain REST (capture/remote.py) so no SDK gate exists."""

    name = "blob"

    def __init__(self, sas_url_secret: str = ""):
        self.sas_url = sas_url_secret

    def enabled(self) -> bool:
        if not self.sas_url:
            return False
        if not self.sas_url.startswith(("http://", "https://")):
            # In-cluster specs carry a Secret NAME here; the Job injects
            # the actual SAS URL as BLOB_URL env (k8s_jobs.job_manifest)
            # and the workload passes it through. A bare name reaching
            # this point means no resolution happened — disable loudly
            # rather than dial a secret name as a URL.
            _log.warning(
                "blob output %r is not a URL (unresolved secret name?); "
                "disabled", self.sas_url,
            )
            return False
        return True

    def output(self, src_file: str) -> str:
        from retina_tpu.capture.remote import BlobStore

        url = BlobStore(self.sas_url).upload(
            os.path.basename(src_file), src_file
        )
        _log.info("capture artifact uploaded: %s", url)
        return url


class S3Output:
    """outputlocation/s3.go — S3 PutObject upload via SigV4 REST
    (capture/remote.py); credentials from the standard AWS env."""

    name = "s3"

    def __init__(self, bucket: str = "", region: str = "",
                 key_prefix: str = "retina/captures", endpoint: str = ""):
        self.bucket, self.region = bucket, region
        # Normalized: a user's trailing slash must not produce '//' keys
        # that the CLI verbs' prefix matching can never find.
        self.key_prefix = key_prefix.rstrip("/") or "retina/captures"
        self.endpoint = endpoint

    def _store(self):
        from retina_tpu.capture.remote import S3Store

        return S3Store(self.bucket, self.region, endpoint=self.endpoint)

    def enabled(self) -> bool:
        if not self.bucket:
            return False
        if not self._store().credentialed():
            _log.warning("s3 output configured but AWS credentials missing")
            return False
        return True

    def output(self, src_file: str) -> str:
        key = f"{self.key_prefix}/{os.path.basename(src_file)}"
        url = self._store().upload(key, src_file)
        _log.info("capture artifact uploaded: %s", url)
        return url


def outputs_from_spec(output: dict) -> list:
    """Build enabled output sinks from a CaptureOutput-shaped dict."""
    sinks = [
        HostPathOutput(output.get("host_path", "")),
        PvcOutput(output.get("persistent_volume_claim", "")),
        BlobOutput(output.get("blob_upload_secret", "")),
        S3Output(**{
            k: v for k, v in (output.get("s3_upload") or {}).items()
            if k in ("bucket", "region", "key_prefix", "endpoint")
        }),
    ]
    return [s for s in sinks if s.enabled()]

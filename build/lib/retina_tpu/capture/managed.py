"""Managed capture storage provisioning.

Reference analog: pkg/capture/outputlocation/managed/storageaccount.go
:1-358 — when a Capture names NO output location and managed storage is
enabled, the operator provisions a storage account (idempotently, found
again across restarts by its ``createdBy=retina`` tag), attaches a
7-day auto-delete lifecycle policy, creates one container per capture
namespace (``retina-capture-<ns>``) with a 3-day immutability window,
and mints a write-only container SAS whose expiry is
``max(2 x capture duration, 10 min)``.

The Azure ARM calls sit behind an injectable :class:`CloudStorageClient`
seam (the azclients.AZClients analog): deployments plug in a real cloud
client; tests plug in a fake and assert the provisioning contract. No
cloud SDK import exists in this module.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Protocol

from retina_tpu.log import logger

DEFAULT_CONTAINER = "retina-capture"
ACCOUNT_PREFIX = "retinacapture"
TAG_CREATED_BY = "createdBy"
TAG_VALUE = "retina"

# SAS expiry floor and multiplier (storageaccount.go:26-37).
EXPIRY_FLOOR_S = 10 * 60
DURATION_MULTIPLIER = 2

RETAIN_BLOB_DAYS = 7  # lifecycle auto-delete (:184-212)
IMMUTABILITY_DAYS = 3  # container immutability window (:29-32)


class CloudStorageClient(Protocol):
    """The cloud-provider seam (azclients.AZClients analog)."""

    def list_accounts(self) -> list[dict]:
        """[{"name": str, "tags": {str: str}}, ...] in the resource
        group."""

    def create_account(self, name: str, params: dict) -> None:
        """Idempotent storage-account creation."""

    def set_management_policy(self, account: str, policy: dict) -> None:
        ...

    def create_container(self, account: str, container: str) -> None:
        ...

    def set_immutability_policy(
        self, account: str, container: str, days: int
    ) -> None:
        ...

    def container_sas_url(
        self, account: str, container: str, expiry_s: float,
        permissions: str,
    ) -> str:
        """Write-scoped container SAS URL."""


class StorageAccountManager:
    """Idempotent managed-storage lifecycle (StorageAccountManager)."""

    def __init__(
        self,
        client: CloudStorageClient,
        unique_container_per_namespace: bool = True,
    ):
        self._log = logger("capture.managed")
        self.client = client
        self.unique_container_per_namespace = unique_container_per_namespace
        self.account: str = ""
        # Container-creation cache (:59-67): creation is idempotent, the
        # cache only trims provider API calls.
        self._containers: set[str] = set()
        self._lock = threading.Lock()

    # -- setup (storageaccount.go:131-227) ----------------------------
    def setup(self) -> None:
        """Find the tagged account from a previous run or create a new
        one, then attach the auto-delete lifecycle policy. Every step is
        idempotent to withstand operator restarts."""
        existing = ""
        for acct in self.client.list_accounts():
            if (acct.get("tags") or {}).get(TAG_CREATED_BY) == TAG_VALUE:
                existing = acct["name"]
                break
        if existing:
            self.account = existing
            self._log.info("using existing storage account %s", existing)
        else:
            # Unique, 3-24 chars, lowercase+digits (:45-51).
            self.account = f"{ACCOUNT_PREFIX}{int(time.time())}"
            self._log.info("creating storage account %s", self.account)
            self.client.create_account(
                self.account,
                {
                    "kind": "StorageV2",
                    "sku": "Standard_LRS",
                    "access_tier": "Cool",
                    "tags": {TAG_CREATED_BY: TAG_VALUE},
                },
            )
        self.client.set_management_policy(
            self.account,
            {
                "rule": "auto-delete",
                "type": "Lifecycle",
                "blob_types": ["blockBlob"],
                "delete_after_days": RETAIN_BLOB_DAYS,
            },
        )
        if not self.unique_container_per_namespace:
            self._ensure_container(DEFAULT_CONTAINER)

    def container_for(self, namespace: str) -> str:
        if not self.unique_container_per_namespace:
            return DEFAULT_CONTAINER
        return f"{DEFAULT_CONTAINER}-{namespace}"

    def _ensure_container(self, container: str) -> None:
        with self._lock:
            if container in self._containers:
                return
        self.client.create_container(self.account, container)
        self.client.set_immutability_policy(
            self.account, container, IMMUTABILITY_DAYS
        )
        with self._lock:
            self._containers.add(container)

    # -- per-capture SAS (storageaccount.go:312-358) ------------------
    def create_container_sas_url(
        self, namespace: str, duration_s: float
    ) -> str:
        if not self.account:
            raise RuntimeError("storage manager not set up")
        container = self.container_for(namespace)
        self._ensure_container(container)
        expiry = max(
            DURATION_MULTIPLIER * duration_s, float(EXPIRY_FLOOR_S)
        )
        url = self.client.container_sas_url(
            self.account, container, expiry, permissions="w"
        )
        self._log.info(
            "minted managed SAS for %s (expiry %.0fs)", container, expiry
        )
        return url


def managed_manager_or_none(
    client: Optional[CloudStorageClient],
) -> Optional[StorageAccountManager]:
    """Construct + set up a manager when a cloud client is configured
    (controller.go:75-81: enabled iff the credential config exists)."""
    if client is None:
        return None
    mgr = StorageAccountManager(client)
    mgr.setup()
    return mgr

"""Capture spec → per-node jobs + packet-filter synthesis.

Reference analog: pkg/capture/crd_to_job.go —
``TranslateCaptureToJobs`` (:352-465): validate the Capture, resolve its
node/pod selectors against the cluster to a node set
(``CalculateCaptureTargetsOnNode`` :622-718), synthesize the
tcpdump/netsh filter from target pod IPs and ports (:483-540, :719-841),
and render one Kubernetes Job per node (:382-464). Here the "cluster" is
the identity cache + a node inventory, and a job is a descriptor the
operator (retina_tpu/operator) schedules as a local worker — same
validation and filter semantics.
"""

from __future__ import annotations

import dataclasses

from retina_tpu.common import RetinaEndpoint, RetinaNode
from retina_tpu.crd.types import Capture, ValidationError


@dataclasses.dataclass
class CaptureJob:
    """One node's capture work item (the batchv1.Job analog)."""

    capture_name: str
    namespace: str
    node_name: str
    filter_expr: str  # tcpdump-syntax packet filter
    duration_s: int
    max_size_mb: int
    packet_size_bytes: int
    output: "dict[str, str]"
    include_metadata: bool = True

    def job_name(self) -> str:
        return f"capture-{self.capture_name}-{self.node_name}"


def _match_labels(selector: dict[str, str], labels: dict[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


def select_pods(
    capture: Capture,
    pods: list[RetinaEndpoint],
    namespace_labels: dict[str, dict[str, str]] | None = None,
) -> list[RetinaEndpoint]:
    """Pod-selector targeting (CalculateCaptureTargetsOnNode pod arm)."""
    t = capture.spec.target
    out = []
    ns_labels = namespace_labels or {}
    for pod in pods:
        if t.namespace_selector:
            if not _match_labels(
                t.namespace_selector, ns_labels.get(pod.namespace, {})
            ):
                continue
        elif pod.namespace != capture.namespace:
            # Without a namespace selector, pod selection is scoped to the
            # Capture's own namespace (reference behavior).
            continue
        if t.pod_selector and not _match_labels(
            t.pod_selector, pod.labels_dict()
        ):
            continue
        out.append(pod)
    return out


def select_nodes(
    capture: Capture,
    nodes: list[RetinaNode],
    node_labels: dict[str, dict[str, str]] | None = None,
    target_pods: list[RetinaEndpoint] | None = None,
) -> list[str]:
    """Node targeting: explicit names, node selector, or the nodes that
    host the selected pods (crd_to_job.go:622-718)."""
    t = capture.spec.target
    if t.node_names:
        known = {n.name for n in nodes}
        missing = [n for n in t.node_names if n not in known]
        if missing:
            raise ValidationError(f"unknown nodes: {missing}")
        return list(t.node_names)
    if t.node_selector:
        labels = node_labels or {}
        sel = [
            n.name for n in nodes
            if _match_labels(t.node_selector, labels.get(n.name, {}))
        ]
        if not sel:
            raise ValidationError("node selector matched no nodes")
        return sel
    # pod-based: nodes hosting the targeted pods
    node_set = sorted({p.node for p in (target_pods or []) if p.node})
    if not node_set:
        raise ValidationError("capture target matched no pods/nodes")
    return node_set


def synthesize_filter(
    pod_ips: list[str],
    extra_filter: str = "",
    ports: list[int] | None = None,
) -> str:
    """tcpdump filter synthesis (crd_to_job.go:483-540,719-841): OR the
    target pod IPs, AND optional ports, AND any raw extra filter."""
    clauses = []
    if pod_ips:
        hosts = " or ".join(f"host {ip}" for ip in sorted(set(pod_ips)))
        clauses.append(f"({hosts})")
    if ports:
        ps = " or ".join(f"port {p}" for p in sorted(set(ports)))
        clauses.append(f"({ps})")
    if extra_filter:
        clauses.append(f"({extra_filter})")
    return " and ".join(clauses)


def translate_capture_to_jobs(
    capture: Capture,
    nodes: list[RetinaNode],
    pods: list[RetinaEndpoint],
    node_labels: dict[str, dict[str, str]] | None = None,
    namespace_labels: dict[str, dict[str, str]] | None = None,
) -> list[CaptureJob]:
    """The TranslateCaptureToJobs entry point (:352)."""
    capture.validate()
    if capture.spec.output.is_empty():
        # Admission is lenient (the operator's managed-storage reconcile
        # may fill the output in); by job-creation time SOME output must
        # exist or the capture artifacts would have nowhere to go.
        raise ValidationError(
            "capture needs at least one output location "
            "(or managed storage enabled)"
        )
    t = capture.spec.target
    if t.pod_selector or t.namespace_selector:
        target_pods = select_pods(capture, pods, namespace_labels)
        node_names = select_nodes(capture, nodes, node_labels, target_pods)
        pod_ips = [ip for p in target_pods for ip in p.ips]
    else:
        target_pods = []
        node_names = select_nodes(capture, nodes, node_labels)
        pod_ips = []
    filt = synthesize_filter(pod_ips, capture.spec.tcpdump_filter)
    out = dataclasses.asdict(capture.spec.output)
    return [
        CaptureJob(
            capture_name=capture.name,
            namespace=capture.namespace,
            node_name=node,
            filter_expr=filt,
            duration_s=capture.spec.duration_s,
            max_size_mb=capture.spec.max_capture_size_mb,
            packet_size_bytes=capture.spec.packet_size_bytes,
            output=out,
            include_metadata=capture.spec.include_metadata,
        )
        for node in node_names
    ]

"""``python -m retina_tpu`` → the retina-tpu CLI."""

import sys

from retina_tpu.cli import main

sys.exit(main())

"""Controllers: identity cache + reconcilers (reference pkg/controllers)."""

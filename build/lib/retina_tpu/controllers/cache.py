"""Node-local identity cache with dense pod-index allocation.

Reference analog: pkg/controllers/cache/cache.go — maps pod-key →
RetinaEndpoint, services, nodes, IP→key indexes, namespace counts, and
publishes object events on pubsub (:17-66 structure, :68-195 getters,
:196-441 updaters). The TPU-specific addition: every endpoint gets a
**stable dense pod index** (index 0 = unknown/world) — the integer the
device-side IdentityMap maps IPs to, and the row index of the pipeline's
per-pod counter rectangles. Freed indices are recycled so the index space
stays ≤ n_pods (the dense tables' static height).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from retina_tpu.common import (
    RetinaEndpoint,
    RetinaNode,
    RetinaSvc,
    TOPIC_NAMESPACES,
    TOPIC_PODS,
    TOPIC_SERVICES,
)
from retina_tpu.events.schema import ip_to_u32
from retina_tpu.log import logger
from retina_tpu.pubsub import PubSub

EventType = str  # "added" | "updated" | "deleted"


class Cache:
    def __init__(self, pubsub: Optional[PubSub] = None, max_pods: int = 1 << 12):
        self._log = logger("cache")
        self._ps = pubsub
        self._lock = threading.RLock()
        self._max_pods = max_pods
        self._eps: dict[str, RetinaEndpoint] = {}
        self._svcs: dict[str, RetinaSvc] = {}
        self._nodes: dict[str, RetinaNode] = {}
        self._ip_to_key: dict[str, str] = {}
        self._ns_counts: dict[str, int] = {}
        self._key_to_index: dict[str, int] = {}
        self._free_indices: list[int] = []
        self._next_index = 1  # 0 reserved for unknown/world
        self._dirty_cbs: list[Callable[[], None]] = []
        # Namespaces carrying the retina.sh=observe annotation — the
        # annotation-driven pod-level opt-in set
        # (cache.AddAnnotatedNamespace, namespace_controller.go:54-62).
        self._annotated_ns: set[str] = set()

    # -- dirty notification (identity table rebuild trigger) ----------
    def on_identity_change(self, cb: Callable[[], None]) -> None:
        self._dirty_cbs.append(cb)

    def _notify(self) -> None:
        for cb in self._dirty_cbs:
            try:
                cb()
            except Exception:
                self._log.exception("identity-change callback failed")

    # -- updaters (cache.go:196-441) ----------------------------------
    def update_endpoint(self, ep: RetinaEndpoint) -> int:
        """Upsert; returns the endpoint's dense pod index."""
        with self._lock:
            key = ep.key()
            prev = self._eps.get(key)
            if prev is None:
                if self._free_indices:
                    idx = self._free_indices.pop()
                elif self._next_index < self._max_pods:
                    idx = self._next_index
                    self._next_index += 1
                else:
                    self._log.warning(
                        "pod index space exhausted (%d); %s mapped to 0",
                        self._max_pods, key,
                    )
                    idx = 0
                if idx:
                    self._key_to_index[key] = idx
                self._ns_counts[ep.namespace] = (
                    self._ns_counts.get(ep.namespace, 0) + 1
                )
            else:
                idx = self._key_to_index.get(key, 0)
                for ip in prev.ips:
                    if self._ip_to_key.get(ip) == key:
                        del self._ip_to_key[ip]
            self._eps[key] = ep
            for ip in ep.ips:
                self._ip_to_key[ip] = key
            ev = "updated" if prev else "added"
        if self._ps:
            self._ps.publish(TOPIC_PODS, (ev, ep))
        self._notify()
        return idx

    def delete_endpoint(self, key: str) -> None:
        with self._lock:
            ep = self._eps.pop(key, None)
            if ep is None:
                return
            for ip in ep.ips:
                if self._ip_to_key.get(ip) == key:
                    del self._ip_to_key[ip]
            idx = self._key_to_index.pop(key, None)
            if idx:
                self._free_indices.append(idx)
            n = self._ns_counts.get(ep.namespace, 0) - 1
            if n <= 0:
                self._ns_counts.pop(ep.namespace, None)
            else:
                self._ns_counts[ep.namespace] = n
        if self._ps:
            self._ps.publish(TOPIC_PODS, ("deleted", ep))
        self._notify()

    def update_service(self, svc: RetinaSvc) -> None:
        with self._lock:
            self._svcs[svc.key()] = svc
            if svc.cluster_ip:
                self._ip_to_key[svc.cluster_ip] = svc.key()
        if self._ps:
            self._ps.publish(TOPIC_SERVICES, ("updated", svc))

    def delete_service(self, key: str) -> None:
        with self._lock:
            svc = self._svcs.pop(key, None)
            if svc and svc.cluster_ip:
                self._ip_to_key.pop(svc.cluster_ip, None)

    def update_node(self, node: RetinaNode) -> None:
        with self._lock:
            self._nodes[node.name] = node

    def list_nodes(self) -> list[RetinaNode]:
        with self._lock:
            return list(self._nodes.values())

    def list_endpoint_keys(self) -> list[str]:
        """All ns/name endpoint keys (informer resync diff support)."""
        with self._lock:
            return list(self._eps.keys())

    def endpoints_in_namespace(self, ns: str) -> list[RetinaEndpoint]:
        with self._lock:
            return [ep for ep in self._eps.values()
                    if ep.namespace == ns]

    # -- annotated namespaces (namespace_controller.go analog) --------
    def set_annotated_namespace(self, ns: str, annotated: bool) -> None:
        with self._lock:
            if annotated == (ns in self._annotated_ns):
                return
            if annotated:
                self._annotated_ns.add(ns)
            else:
                self._annotated_ns.discard(ns)
        if self._ps:
            self._ps.publish(
                TOPIC_NAMESPACES,
                ("annotated" if annotated else "unannotated", ns),
            )

    def annotated_namespaces(self) -> set[str]:
        with self._lock:
            return set(self._annotated_ns)

    def list_service_keys(self) -> list[str]:
        with self._lock:
            return list(self._svcs.keys())

    # -- getters (cache.go:68-195) ------------------------------------
    def get_obj_by_ip(self, ip: str):
        with self._lock:
            key = self._ip_to_key.get(ip)
            if key is None:
                return None
            return self._eps.get(key) or self._svcs.get(key)

    def get_endpoint(self, key: str) -> Optional[RetinaEndpoint]:
        with self._lock:
            return self._eps.get(key)

    def get_index(self, key: str) -> int:
        with self._lock:
            return self._key_to_index.get(key, 0)

    def endpoint_by_index(self, idx: int) -> Optional[RetinaEndpoint]:
        with self._lock:
            for k, i in self._key_to_index.items():
                if i == idx:
                    return self._eps.get(k)
        return None

    def namespace_count(self, ns: str) -> int:
        with self._lock:
            return self._ns_counts.get(ns, 0)

    def pod_count(self) -> int:
        with self._lock:
            return len(self._eps)

    # -- device identity table source ---------------------------------
    def ip_index_map(self) -> dict[int, int]:
        """{ipv4 u32 → pod index} for IdentityMap.build_host."""
        out: dict[int, int] = {}
        with self._lock:
            for key, idx in self._key_to_index.items():
                ep = self._eps.get(key)
                if ep is None or idx == 0:
                    continue
                for ip in ep.ips:
                    try:
                        out[ip_to_u32(ip)] = idx
                    except (ValueError, AttributeError):
                        continue  # IPv6/hostnames: not device-mapped yet
        return out

    def index_label_map(self) -> dict[int, RetinaEndpoint]:
        """{pod index → endpoint} for scrape-time label attachment."""
        with self._lock:
            return {
                idx: self._eps[key]
                for key, idx in self._key_to_index.items()
                if key in self._eps
            }

"""Fixed-width flow-event record schema.

The reference's universal contract between data plane and control plane is a
`flow.Flow` protobuf built from the eBPF `struct packet`
(reference: pkg/plugin/conntrack/_cprog/conntrack.c:33-49 fields t_nsec,
bytes, src_ip, dst_ip, ports, tcp metadata, observation_point,
traffic_direction, proto, flags, is_reply; pkg/utils/flow_utils.go:33-130
maps observation point -> direction/verdict).

A protobuf-per-event design cannot feed a TPU: XLA wants dense, statically
shaped tensors. So the TPU-native contract is a **structure-of-arrays
uint32 record**: one event = NUM_FIELDS uint32 lanes, one batch =
a (B, NUM_FIELDS) uint32 array (64 bytes/event, cacheline-sized — same
budget as the reference's perf-ring record). Field semantics:

==  =============  =====================================================
ix  name           meaning
==  =============  =====================================================
0   TS_LO          low 32 bits of nanosecond timestamp
1   TS_HI          high 32 bits of nanosecond timestamp
2   SRC_IP         IPv4 source, host byte order
3   DST_IP         IPv4 destination, host byte order
4   PORTS          src_port << 16 | dst_port
5   META           proto << 24 | tcp_flags << 16 | obs_point << 8
                   | direction << 4 | is_reply
6   BYTES          L3 length of the packet/flow-report
7   PACKETS        packet count (1 for per-packet events, N for
                   conntrack-sampled flow reports)
8   VERDICT        flow verdict (FORWARDED / DROPPED / ...)
9   DROP_REASON    drop reason id (valid when VERDICT == DROPPED)
10  TSVAL          TCP timestamp option TSval (network order, as u32)
11  TSECR          TCP timestamp option TSecr
12  DNS            qtype << 16 | rcode << 8 | dns_event_kind
13  DNS_QHASH      32-bit hash of the DNS query name (host supplies
                   the hash; string table lives host-side)
14  EVENT_TYPE     EV_* discriminator (forward/drop/dns/retrans/...)
15  IFINDEX        interface index the event was observed on
==  =============  =====================================================

All columns are uint32; 64-bit quantities (timestamps, conntrack byte
counters) are split lo/hi. Strings never cross the host->device boundary:
identities travel as dense indices (see retina_tpu.enrich) and DNS names as
hashes with a host-side string table, because TPUs do not do strings.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator

import numpy as np

# ---------------------------------------------------------------------------
# Field indices


class F:
    """Column indices of the event record."""

    TS_LO = 0
    TS_HI = 1
    SRC_IP = 2
    DST_IP = 3
    PORTS = 4
    META = 5
    BYTES = 6
    PACKETS = 7
    VERDICT = 8
    DROP_REASON = 9
    TSVAL = 10
    TSECR = 11
    DNS = 12
    DNS_QHASH = 13
    EVENT_TYPE = 14
    IFINDEX = 15


NUM_FIELDS = 16
RECORD_BYTES = NUM_FIELDS * 4  # 64 bytes, one cacheline

# Observation points (reference: pkg/utils/flow_utils.go:72-92).
OP_TO_STACK = 0  # container -> host stack   => egress
OP_TO_ENDPOINT = 1  # host stack -> container   => ingress
OP_FROM_NETWORK = 2  # network -> host           => ingress
OP_TO_NETWORK = 3  # host -> network           => egress

# Traffic direction.
DIR_UNKNOWN = 0
DIR_INGRESS = 1
DIR_EGRESS = 2

# Verdicts (subset of flow.Verdict used by the reference).
VERDICT_UNKNOWN = 0
VERDICT_FORWARDED = 1
VERDICT_DROPPED = 2

# Event types (reference plugins that emit them, SURVEY.md §2.2).
EV_FORWARD = 0  # packetparser / packetforward
EV_DROP = 1  # dropreason
EV_DNS_REQ = 2  # dns
EV_DNS_RESP = 3  # dns
EV_TCP_RETRANS = 4  # tcpretrans

PROTO_TCP = 6
PROTO_UDP = 17

# TCP flag bits, standard wire order.
TCP_FIN = 1 << 0
TCP_SYN = 1 << 1
TCP_RST = 1 << 2
TCP_PSH = 1 << 3
TCP_ACK = 1 << 4
TCP_URG = 1 << 5
TCP_ECE = 1 << 6
TCP_CWR = 1 << 7

TCP_FLAG_NAMES = {
    TCP_FIN: "FIN",
    TCP_SYN: "SYN",
    TCP_RST: "RST",
    TCP_PSH: "PSH",
    TCP_ACK: "ACK",
    TCP_URG: "URG",
    TCP_ECE: "ECE",
    TCP_CWR: "CWR",
}


def pack_meta(
    proto: int,
    tcp_flags: int = 0,
    obs_point: int = OP_FROM_NETWORK,
    direction: int = DIR_UNKNOWN,
    is_reply: int = 0,
) -> int:
    return (
        ((proto & 0xFF) << 24)
        | ((tcp_flags & 0xFF) << 16)
        | ((obs_point & 0xFF) << 8)
        | ((direction & 0xF) << 4)
        | (is_reply & 0xF)
    )


def pack_ports(src_port: int, dst_port: int) -> int:
    return ((src_port & 0xFFFF) << 16) | (dst_port & 0xFFFF)


def obs_point_to_direction(obs_point: int) -> int:
    """Observation point -> traffic direction (flow_utils.go:72-92)."""
    if obs_point in (OP_TO_STACK, OP_TO_NETWORK):
        return DIR_EGRESS
    if obs_point in (OP_TO_ENDPOINT, OP_FROM_NETWORK):
        return DIR_INGRESS
    return DIR_UNKNOWN


def ip_to_u32(ip: str) -> int:
    a, b, c, d = (int(x) for x in ip.split("."))
    return (a << 24) | (b << 16) | (c << 8) | d


def u32_to_ip(v: int) -> str:
    return f"{(v >> 24) & 0xFF}.{(v >> 16) & 0xFF}.{(v >> 8) & 0xFF}.{v & 0xFF}"


# ---------------------------------------------------------------------------
# Batches


@dataclasses.dataclass
class EventBatch:
    """A fixed-capacity batch of event records plus a validity count.

    ``records`` is always shaped (capacity, NUM_FIELDS) so every batch of a
    given capacity hits the same compiled executable; ``n_valid`` marks how
    many leading rows are real. Device kernels mask on an iota < n_valid
    comparison instead of slicing (dynamic shapes would force recompiles —
    the reference's analog constraint is its fixed 32-page perf buffers,
    packetparser types_linux.go:67-69).
    """

    records: np.ndarray  # (capacity, NUM_FIELDS) uint32
    n_valid: int

    def __post_init__(self) -> None:
        assert self.records.ndim == 2 and self.records.shape[1] == NUM_FIELDS
        assert self.records.dtype == np.uint32
        assert 0 <= self.n_valid <= self.records.shape[0]

    @property
    def capacity(self) -> int:
        return int(self.records.shape[0])

    @classmethod
    def empty(cls, capacity: int) -> "EventBatch":
        return cls(np.zeros((capacity, NUM_FIELDS), np.uint32), 0)

    def valid_rows(self) -> np.ndarray:
        return self.records[: self.n_valid]


class EventBuilder:
    """Host-side builder producing EventBatches from per-event calls.

    This sits where the reference's perf-ring decode workers sit
    (packetparser_linux.go:556-652): per-event ingestion on the host,
    emitting dense batches for the device.
    """

    def __init__(self, capacity: int):
        self._batch = EventBatch.empty(capacity)
        self._full: list[EventBatch] = []

    def add(
        self,
        *,
        ts_ns: int = 0,
        src_ip: int = 0,
        dst_ip: int = 0,
        src_port: int = 0,
        dst_port: int = 0,
        proto: int = PROTO_TCP,
        tcp_flags: int = 0,
        obs_point: int = OP_FROM_NETWORK,
        is_reply: int = 0,
        bytes_: int = 0,
        packets: int = 1,
        verdict: int = VERDICT_FORWARDED,
        drop_reason: int = 0,
        tsval: int = 0,
        tsecr: int = 0,
        dns: int = 0,
        dns_qhash: int = 0,
        event_type: int = EV_FORWARD,
        ifindex: int = 0,
    ) -> None:
        b = self._batch
        if b.n_valid == b.capacity:
            self._full.append(b)
            self._batch = b = EventBatch.empty(b.capacity)
        row = b.records[b.n_valid]
        row[F.TS_LO] = ts_ns & 0xFFFFFFFF
        row[F.TS_HI] = (ts_ns >> 32) & 0xFFFFFFFF
        row[F.SRC_IP] = src_ip
        row[F.DST_IP] = dst_ip
        row[F.PORTS] = pack_ports(src_port, dst_port)
        row[F.META] = pack_meta(
            proto, tcp_flags, obs_point, obs_point_to_direction(obs_point), is_reply
        )
        row[F.BYTES] = bytes_
        row[F.PACKETS] = packets
        row[F.VERDICT] = verdict
        row[F.DROP_REASON] = drop_reason
        row[F.TSVAL] = tsval
        row[F.TSECR] = tsecr
        row[F.DNS] = dns
        row[F.DNS_QHASH] = dns_qhash
        row[F.EVENT_TYPE] = event_type
        row[F.IFINDEX] = ifindex
        b.n_valid += 1

    def drain(self) -> Iterator[EventBatch]:
        """Yield all full batches plus the current partial one."""
        full, self._full = self._full, []
        yield from full
        if self._batch.n_valid:
            out, self._batch = self._batch, EventBatch.empty(self._batch.capacity)
            yield out

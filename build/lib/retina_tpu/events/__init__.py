"""Event data plane: record schema, batching, and sources.

Reference analog: the eBPF `struct packet` (conntrack.c:33-49) carried over
perf rings into `flow.Flow` protobufs (pkg/utils/flow_utils.go:33-130).
Here an event is a row of fixed-width uint32 columns so a batch is a dense
(B, NUM_FIELDS) device tensor — the shape the TPU vector units want.
"""

from retina_tpu.events.schema import (  # noqa: F401
    EventBatch,
    F,
    NUM_FIELDS,
    RECORD_BYTES,
    DIR_INGRESS,
    DIR_EGRESS,
    OP_TO_STACK,
    OP_TO_ENDPOINT,
    OP_FROM_NETWORK,
    OP_TO_NETWORK,
    VERDICT_FORWARDED,
    VERDICT_DROPPED,
    EV_FORWARD,
    EV_DROP,
    EV_DNS_REQ,
    EV_DNS_RESP,
    EV_TCP_RETRANS,
    PROTO_TCP,
    PROTO_UDP,
    TCP_FIN,
    TCP_SYN,
    TCP_RST,
    TCP_PSH,
    TCP_ACK,
    TCP_URG,
    TCP_ECE,
    TCP_CWR,
)

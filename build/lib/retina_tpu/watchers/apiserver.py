"""API-server watcher.

Reference analog: pkg/watchers/apiserver — periodically resolves the
apiserver hostname to IPs, diffs against the last set, publishes to the
cache and adds the IPs to the filtermanager (apiserver.go:29-60), with DNS
retry (:25-27). Same here, plus pushing the IPs into the engine for the
apiserver-latency matcher (models/pipeline.py latency block).
"""

from __future__ import annotations

import socket
from typing import Callable, Optional

from retina_tpu.common import TOPIC_APISERVER, retry
from retina_tpu.events.schema import ip_to_u32
from retina_tpu.log import logger
from retina_tpu.managers.filtermanager import FilterManager
from retina_tpu.pubsub import PubSub


class ApiServerWatcher:
    name = "apiserver"

    def __init__(
        self,
        pubsub: PubSub,
        host: str = "kubernetes.default.svc",
        filtermanager: Optional[FilterManager] = None,
        on_ips: Optional[Callable[[list[int]], None]] = None,
        resolver: Optional[Callable[[str], list[str]]] = None,
    ):
        self._log = logger("watcher.apiserver")
        self._ps = pubsub
        self._host = host
        self._fm = filtermanager
        self._on_ips = on_ips
        self._resolve = resolver or self._dns_resolve
        self._current: set[str] = set()

    @staticmethod
    def _dns_resolve(host: str) -> list[str]:
        infos = socket.getaddrinfo(host, 443, socket.AF_INET)
        return sorted({i[4][0] for i in infos})

    def refresh(self) -> None:
        try:
            ips = set(retry(lambda: self._resolve(self._host), attempts=3,
                            base_delay_s=0.1))
        except OSError as e:
            self._log.warning("apiserver resolve failed: %s", e)
            return
        if ips == self._current:
            return
        added = sorted(ips - self._current)
        removed = sorted(self._current - ips)
        self._current = ips
        self._log.info("apiserver IPs: %s", sorted(ips))
        u32 = [ip_to_u32(ip) for ip in sorted(ips)]
        if self._fm is not None:
            if added:
                self._fm.add_ips([ip_to_u32(i) for i in added],
                                 "apiserver-watcher", "apiserver")
            if removed:
                self._fm.delete_ips([ip_to_u32(i) for i in removed],
                                    "apiserver-watcher", "apiserver")
        if self._on_ips is not None:
            self._on_ips(u32)
        self._ps.publish(TOPIC_APISERVER, sorted(ips))

"""Host watchers (reference pkg/watchers): endpoint + apiserver."""

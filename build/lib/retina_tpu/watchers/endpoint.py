"""Endpoint (interface) watcher.

Reference analog: pkg/watchers/endpoint — snapshot-diffs host veths via
netlink (endpoint_linux.go:54) and publishes EndpointCreated/Deleted on
pubsub (endpoint.go:56-85). Host analog: snapshot-diff /sys/class/net
interfaces (veth detection via the device symlink) on each Refresh.
"""

from __future__ import annotations

import os
from pathlib import Path

from retina_tpu.common import TOPIC_ENDPOINTS
from retina_tpu.log import logger
from retina_tpu.pubsub import PubSub


class EndpointWatcher:
    name = "endpoint"

    def __init__(self, pubsub: PubSub, sys_root: str = "/sys"):
        self._log = logger("watcher.endpoint")
        self._ps = pubsub
        self._sys = sys_root
        self._known: set[str] = set()

    def _snapshot(self) -> set[str]:
        base = Path(f"{self._sys}/class/net")
        try:
            return set(os.listdir(base))
        except OSError:
            return set()

    def refresh(self) -> None:
        cur = self._snapshot()
        created = cur - self._known
        deleted = self._known - cur
        self._known = cur
        for name in sorted(created):
            self._ps.publish(TOPIC_ENDPOINTS, ("created", name))
        for name in sorted(deleted):
            self._ps.publish(TOPIC_ENDPOINTS, ("deleted", name))
        if created or deleted:
            self._log.info(
                "interfaces: +%d -%d (total %d)",
                len(created), len(deleted), len(cur),
            )

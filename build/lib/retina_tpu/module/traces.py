"""Traces module: per-flow trace sampling.

Reference analog: pkg/module/traces — a skeleton that only stores
``TracesSpec`` reconciles (traces_module.go; the trace pipeline itself
never landed). This module goes further while keeping the same CRD
surface: a reconciled spec compiles into vectorized record matchers, an
engine observer samples matching rows off the live feed (bounded rings,
per-mille sampling — the observer runs on the feed thread and must stay
O(numpy) per block), and the sampled flow traces are queryable through
``/debug/vars`` (CLI ``retina-tpu trace``).

TracesSpec mapping (crd/types.py):
- ``trace_targets``: list of {"name", "ips": [dotted-quads],
  "ports": [ints], "protocols": ["tcp"|"udp"]} — a row matches a target
  if src OR dst IP is listed (empty = any), and similarly for ports /
  protocols.
- ``trace_points``: subset of {"ingress", "egress"} (empty = both),
  matched against the record's traffic direction.
- ``sampling_rate_per_mille``: 0 or 1000 = keep every matching row;
  else keep rows whose flow hash falls under rate/1000 — sampling is
  per FLOW (hash of the canonical 5-tuple), so a sampled flow's whole
  trace is kept rather than random rows of many flows.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any

import numpy as np

from retina_tpu.crd.types import TracesConfiguration, TracesSpec
from retina_tpu.events.schema import (
    DIR_EGRESS,
    DIR_INGRESS,
    F,
    PROTO_TCP,
    PROTO_UDP,
    ip_to_u32,
    u32_to_ip,
)
from retina_tpu.log import logger

MAX_EVENTS_PER_TARGET = 512  # bounded ring per target
MAX_ROWS_PER_BLOCK = 64  # per-block cap: the observer must stay cheap

_PROTO = {"tcp": PROTO_TCP, "udp": PROTO_UDP}
_DIR = {"ingress": DIR_INGRESS, "egress": DIR_EGRESS}


class _Target:
    __slots__ = ("name", "ips", "ports", "protos")

    def __init__(self, name: str, ips: set[int], ports: set[int],
                 protos: set[int]):
        self.name = name
        # Arrays precomputed HERE: observe() runs per record block on
        # the feed thread and must not rebuild them per call.
        self.ips = (
            np.fromiter(ips, np.uint32, len(ips)) if ips else None
        )
        self.ports = (
            np.fromiter(ports, np.uint32, len(ports)) if ports else None
        )
        self.protos = protos


class TracesModule:
    def __init__(self) -> None:
        self._log = logger("tracesmodule")
        self._lock = threading.Lock()
        self._spec: TracesSpec | None = None
        self._targets: list[_Target] = []
        self._dirs: set[int] = set()
        self._per_mille = 1000
        self._rings: dict[str, collections.deque] = {}
        self._matched = 0

    # -- wiring --------------------------------------------------------
    def attach(self, engine: Any) -> None:
        """Register as an engine observer (the dns/hubble seam) — every
        accepted record block flows through :meth:`observe`."""
        engine.add_observer(self.observe)

    # -- reconcile (traces_module.go Reconcile analog) -----------------
    def reconcile(self, conf: TracesConfiguration) -> None:
        targets: list[_Target] = []
        for i, t in enumerate(conf.spec.trace_targets):
            try:
                ips = {ip_to_u32(ip) for ip in t.get("ips", [])}
                ports = {int(p) for p in t.get("ports", [])}
                protos = {
                    _PROTO[p.lower()]
                    for p in t.get("protocols", [])
                    if p.lower() in _PROTO
                }
                targets.append(
                    _Target(str(t.get("name", f"target-{i}")),
                            ips, ports, protos)
                )
            except (ValueError, AttributeError, TypeError) as e:
                self._log.warning("trace target %d invalid: %s", i, e)
        dirs = {_DIR[p] for p in conf.spec.trace_points if p in _DIR}
        rate = int(conf.spec.sampling_rate_per_mille) or 1000
        with self._lock:
            self._spec = conf.spec
            self._targets = targets
            self._dirs = dirs
            self._per_mille = max(1, min(rate, 1000))
            self._rings = {
                t.name: self._rings.get(
                    t.name,
                    collections.deque(maxlen=MAX_EVENTS_PER_TARGET),
                )
                for t in targets
            }
        self._log.info(
            "traces reconciled: %d target(s), points=%s, %d/1000 flows",
            len(targets),
            sorted(conf.spec.trace_points) or "any",
            self._per_mille,
        )

    def active_spec(self) -> TracesSpec | None:
        with self._lock:
            return self._spec

    # -- sampling (engine observer; feed thread — stay vectorized) -----
    def observe(self, rec: np.ndarray, plugin: str) -> None:
        with self._lock:
            targets = self._targets
            dirs = self._dirs
            per_mille = self._per_mille
        if not targets or len(rec) == 0:
            return
        src = rec[:, F.SRC_IP]
        dst = rec[:, F.DST_IP]
        ports = rec[:, F.PORTS]
        sport = ports >> np.uint32(16)
        dport = ports & np.uint32(0xFFFF)
        meta = rec[:, F.META]
        proto = meta >> np.uint32(24)
        direction = (meta >> np.uint32(4)) & np.uint32(0xF)
        base = np.ones(len(rec), bool)
        if dirs:
            dmask = np.zeros(len(rec), bool)
            for d in dirs:
                dmask |= direction == d
            base &= dmask
        if per_mille < 1000:
            # Flow-consistent sampling: hash the canonical 5-tuple so a
            # sampled flow keeps its WHOLE trace across blocks.
            from retina_tpu.parallel.partition import canonical_conn_hash

            base &= (
                canonical_conn_hash(rec) % np.uint32(1000)
            ) < np.uint32(per_mille)
        if not base.any():
            return
        now = time.time()
        for tgt in targets:
            m = base
            if tgt.ips is not None:
                m = m & (np.isin(src, tgt.ips) | np.isin(dst, tgt.ips))
            if tgt.ports is not None:
                m = m & (
                    np.isin(sport, tgt.ports)
                    | np.isin(dport, tgt.ports)
                )
            if tgt.protos:
                pmask = np.zeros(len(rec), bool)
                for p in tgt.protos:
                    pmask |= proto == p
                m = m & pmask
            idx = np.flatnonzero(m)[:MAX_ROWS_PER_BLOCK]
            if len(idx) == 0:
                continue
            rows = rec[idx]
            events = [
                {
                    "ts": now,
                    "plugin": plugin,
                    "src": u32_to_ip(int(r[F.SRC_IP])),
                    "dst": u32_to_ip(int(r[F.DST_IP])),
                    "sport": int(r[F.PORTS]) >> 16,
                    "dport": int(r[F.PORTS]) & 0xFFFF,
                    "proto": int(r[F.META]) >> 24,
                    "direction": (int(r[F.META]) >> 4) & 0xF,
                    "verdict": int(r[F.VERDICT]),
                    "drop_reason": int(r[F.DROP_REASON]),
                    "event_type": int(r[F.EVENT_TYPE]),
                    "bytes": int(r[F.BYTES]),
                    "packets": int(r[F.PACKETS]),
                }
                for r in rows
            ]
            with self._lock:
                ring = self._rings.get(tgt.name)
                if ring is not None:
                    ring.extend(events)
                    self._matched += len(events)

    # -- query (CLI `trace` via /debug/vars) ---------------------------
    def traces(self, target: str | None = None,
               limit: int = 100) -> dict[str, list[dict]]:
        with self._lock:
            names = [target] if target else list(self._rings)
            return {
                n: list(self._rings[n])[-limit:]
                for n in names
                if n in self._rings
            }

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "targets": [t.name for t in self._targets],
                "events_sampled": self._matched,
                "per_target": {n: len(r) for n, r in self._rings.items()},
            }

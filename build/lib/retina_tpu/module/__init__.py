"""Aggregation modules (reference pkg/module): metrics + traces."""

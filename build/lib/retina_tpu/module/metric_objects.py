"""Advanced (pod-level) metric objects.

Reference analog: pkg/module/metrics/*.go — per-metric aggregators
implementing ``AdvMetricsInterface{Init, ProcessFlow, Clean}`` (types.go),
e.g. ForwardMetrics.ProcessFlow incrementing a GaugeVec per flow
(forward.go:97-171). The TPU redesign inverts the dataflow: aggregation
already happened on device (the pipeline step), so each object implements
``publish(snapshot, ctx)`` — read its slice of the merged device snapshot
and set labeled gauges. Per-flow CPU work is gone; publish cost is
O(active label sets), not O(events).

Local vs remote context (metrics_module.go:216-222, modes doc): local
context publishes per-pod series from the dense rectangles; remote context
publishes src×dst pod-pair series from the service-graph heavy-hitter
sketch — bounded by the sketch's slot count where the reference's remote
mode is unbounded.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np

from retina_tpu.common import RetinaEndpoint
from retina_tpu.crd.types import MetricsContextOptions, MetricsNamespaces
from retina_tpu.exporter import Exporter
from retina_tpu.utils import metric_names as mn


@dataclasses.dataclass
class PublishCtx:
    """Everything a metric object needs at publish time."""

    labeler: dict[int, RetinaEndpoint]  # pod index -> identity
    namespaces: MetricsNamespaces
    remote_context: bool = False
    dns_resolver: Any = None  # qname hash -> str
    top_k: int = 50

    def admit(self, idx: int) -> Optional[RetinaEndpoint]:
        ep = self.labeler.get(idx)
        if ep is None:
            return None
        return ep if self.namespaces.admits(ep.namespace) else None


_POD_LABELS = [mn.L_POD, mn.L_NAMESPACE, mn.L_WORKLOAD]


def _pod_label_values(ep: RetinaEndpoint) -> dict[str, str]:
    return {
        mn.L_POD: ep.name,
        mn.L_NAMESPACE: ep.namespace,
        mn.L_WORKLOAD: ep.workload(),
    }


class AdvMetricBase:
    """Init/publish/clean contract (AdvMetricsInterface analog)."""

    name = ""

    def __init__(self, opts: MetricsContextOptions, exporter: Exporter):
        self.opts = opts
        self.exporter = exporter
        self.init()

    def init(self) -> None:  # pragma: no cover - overridden
        raise NotImplementedError

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        raise NotImplementedError

    def clean(self) -> None:
        """Gauges live in the advanced registry; reset drops them."""


class ForwardMetrics(AdvMetricBase):
    name = "forward"

    def init(self) -> None:
        labels = [mn.L_DIRECTION, *_POD_LABELS]
        self.count = self.exporter.new_adv_gauge(mn.ADV_FORWARD_COUNT, labels)
        self.bytes = self.exporter.new_adv_gauge(mn.ADV_FORWARD_BYTES, labels)

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        pf = snap["pod_forward"]  # (P, 2 dir, 2 {pkts, bytes})
        active = np.nonzero(pf.sum(axis=(1, 2)))[0]
        for idx in active:
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            lv = _pod_label_values(ep)
            for d, dname in ((0, "ingress"), (1, "egress")):
                self.count.labels(direction=dname, **lv).set(int(pf[idx, d, 0]))
                self.bytes.labels(direction=dname, **lv).set(int(pf[idx, d, 1]))


class DropMetrics(AdvMetricBase):
    name = "drop"

    def init(self) -> None:
        labels = [mn.L_REASON, *_POD_LABELS]
        self.count = self.exporter.new_adv_gauge(mn.ADV_DROP_COUNT, labels)
        self.bytes = self.exporter.new_adv_gauge(mn.ADV_DROP_BYTES, labels)

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        from retina_tpu.plugins.dropreason import DROP_REASONS

        pd = snap["pod_drop"]  # (P, R, 2)
        pods, reasons = np.nonzero(pd[:, :, 0])
        for idx, r in zip(pods, reasons):
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            lv = _pod_label_values(ep)
            rname = DROP_REASONS.get(int(r), str(int(r)))
            self.count.labels(reason=rname, **lv).set(int(pd[idx, r, 0]))
            self.bytes.labels(reason=rname, **lv).set(int(pd[idx, r, 1]))


class TcpFlagsMetrics(AdvMetricBase):
    name = "tcpflags"

    _FLAGS = ["FIN", "SYN", "RST", "PSH", "ACK", "URG", "ECE", "CWR"]

    def init(self) -> None:
        self.count = self.exporter.new_adv_gauge(
            mn.ADV_TCP_FLAG_COUNTERS, [mn.L_FLAG, *_POD_LABELS]
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        tf = snap["pod_tcpflags"]  # (P, 8)
        pods, bits = np.nonzero(tf)
        for idx, bit in zip(pods, bits):
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            self.count.labels(
                flag=self._FLAGS[int(bit)], **_pod_label_values(ep)
            ).set(int(tf[idx, bit]))


class TcpRetransMetrics(AdvMetricBase):
    name = "tcpretrans"

    def init(self) -> None:
        self.count = self.exporter.new_adv_gauge(
            mn.ADV_TCP_RETRANS_COUNT, _POD_LABELS
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        pr = snap["pod_retrans"]  # (P,)
        for idx in np.nonzero(pr)[0]:
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            self.count.labels(**_pod_label_values(ep)).set(int(pr[idx]))


class DnsMetrics(AdvMetricBase):
    name = "dns"

    _QTYPES = {1: "A", 5: "CNAME", 28: "AAAA", 12: "PTR"}

    def init(self) -> None:
        self.req = self.exporter.new_adv_gauge(
            mn.ADV_DNS_REQUEST_COUNT, [mn.L_QTYPE, *_POD_LABELS]
        )
        self.resp = self.exporter.new_adv_gauge(
            mn.ADV_DNS_RESPONSE_COUNT, [mn.L_QTYPE, *_POD_LABELS]
        )
        self.heavy = self.exporter.new_adv_gauge(
            mn.HEAVY_HITTER_DNS, ["query"]
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        pdns = snap["pod_dns"]  # (P, Q, 2)
        pods, qtypes = np.nonzero(pdns.sum(axis=2))
        for idx, qt in zip(pods, qtypes):
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            lv = _pod_label_values(ep)
            qname = self._QTYPES.get(int(qt), str(int(qt)))
            self.req.labels(query_type=qname, **lv).set(int(pdns[idx, qt, 0]))
            self.resp.labels(query_type=qname, **lv).set(int(pdns[idx, qt, 1]))
        # qname heavy hitters, resolved through the host string table
        if ctx.dns_resolver is not None and "dns_hh" in snap:
            from retina_tpu.parallel.telemetry import topk_from_snapshot

            keys, counts = topk_from_snapshot(snap, "dns_hh", ctx.top_k)
            for key, cnt in zip(keys, counts):
                self.heavy.labels(
                    query=ctx.dns_resolver(int(key[0]))
                ).set(int(cnt))


class LatencyMetrics(AdvMetricBase):
    """Apiserver RTT histogram (reference latency.go:286-301)."""

    name = "latency"

    def init(self) -> None:
        self.hist = self.exporter.new_adv_gauge(
            mn.ADV_API_LATENCY, [mn.L_BUCKET]
        )
        self.no_resp = self.exporter.new_adv_gauge(mn.ADV_API_NO_RESPONSE, [])

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        hist = snap["lat_hist"]  # (H,) exponential ms buckets
        for b in range(len(hist)):
            self.hist.labels(le_ms=str((1 << b) - 1)).set(int(hist[b]))


class DistinctSourcesMetrics(AdvMetricBase):
    """Per-pod distinct source IPs from the HLL bank (new capability the
    reference cannot express with bounded memory)."""

    name = "distinct_sources"

    def init(self) -> None:
        self.gauge = self.exporter.new_adv_gauge(
            mn.DISTINCT_SRC_PER_POD, _POD_LABELS
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        est = snap["hll_src_per_pod"]  # (P,) float estimates
        for idx in np.nonzero(est >= 1.0)[0]:
            ep = ctx.admit(int(idx))
            if ep is None:
                continue
            self.gauge.labels(**_pod_label_values(ep)).set(float(est[idx]))


class FlowsMetrics(AdvMetricBase):
    """Flow-level series: distinct 5-tuples + top flow heavy hitters."""

    name = "flows"

    def init(self) -> None:
        self.distinct = self.exporter.new_adv_gauge(mn.DISTINCT_FLOWS, [])
        self.heavy = self.exporter.new_adv_gauge(
            mn.HEAVY_HITTER_FLOWS,
            ["src_ip", "dst_ip", "src_port", "dst_port", mn.L_PROTO],
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        from retina_tpu.events.schema import u32_to_ip
        from retina_tpu.parallel.telemetry import topk_from_snapshot

        self.distinct.set(float(snap["hll_flows"][0]))
        keys, counts = topk_from_snapshot(snap, "flow_hh", ctx.top_k)
        for key, cnt in zip(keys, counts):
            src, dst, ports, proto = (int(k) for k in key)
            self.heavy.labels(
                src_ip=u32_to_ip(src), dst_ip=u32_to_ip(dst),
                src_port=str(ports >> 16), dst_port=str(ports & 0xFFFF),
                protocol={6: "TCP", 17: "UDP"}.get(proto, str(proto)),
            ).set(int(cnt))


class ServicesMetrics(AdvMetricBase):
    """Pod×pod service-graph edges from the svc heavy-hitter sketch —
    the REMOTE-context mode (src×dst pairs) with bounded memory."""

    name = "services"

    def init(self) -> None:
        self.edges = self.exporter.new_adv_gauge(
            mn.HEAVY_HITTER_SERVICES,
            ["src_" + mn.L_POD, "src_" + mn.L_NAMESPACE,
             "dst_" + mn.L_POD, "dst_" + mn.L_NAMESPACE],
        )

    def publish(self, snap: dict[str, Any], ctx: PublishCtx) -> None:
        from retina_tpu.parallel.telemetry import topk_from_snapshot

        keys, counts = topk_from_snapshot(snap, "svc_hh", ctx.top_k)
        for key, cnt in zip(keys, counts):
            src = ctx.admit(int(key[0]))
            dst = ctx.admit(int(key[1]))
            if src is None or dst is None:
                continue
            self.edges.labels(
                src_podname=src.name, src_namespace=src.namespace,
                dst_podname=dst.name, dst_namespace=dst.namespace,
            ).set(int(cnt))


METRIC_CONSTRUCTORS = {
    cls.name: cls
    for cls in (
        ForwardMetrics, DropMetrics, TcpFlagsMetrics, TcpRetransMetrics,
        DnsMetrics, LatencyMetrics, DistinctSourcesMetrics, FlowsMetrics,
        ServicesMetrics,
    )
}

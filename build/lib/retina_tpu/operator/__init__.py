"""Operator: cluster-scoped reconcilers (reference operator/ +
pkg/controllers/operator)."""

from retina_tpu.operator.store import CRDStore
from retina_tpu.operator.operator import Operator

"""In-process CRD store — the kube-apiserver seam.

The reference's controllers watch CRs through controller-runtime informers
backed by a real apiserver (unit-tested with envtest, SURVEY.md §4). With
no cluster here, this store IS that seam: typed objects keyed by
(kind, namespace/name), with apply/delete firing registered watchers —
the informer contract the operator's reconcilers consume. Tests drive it
directly, the CLI drives it via YAML files, and a future k8s bridge would
replace it without touching the reconcilers.
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from retina_tpu.log import logger

WatchFn = Callable[[str, Any], None]  # (event, obj); event: applied|deleted


class CRDStore:
    def __init__(self) -> None:
        self._log = logger("crdstore")
        self._lock = threading.RLock()
        self._objs: dict[str, dict[str, Any]] = {}
        self._watchers: dict[str, list[WatchFn]] = {}

    @staticmethod
    def _key(obj: Any) -> str:
        ns = getattr(obj, "namespace", "") or "default"
        return f"{ns}/{obj.name}"

    def apply(self, kind: str, obj: Any) -> None:
        if hasattr(obj, "validate"):
            obj.validate()
        with self._lock:
            self._objs.setdefault(kind, {})[self._key(obj)] = obj
            watchers = list(self._watchers.get(kind, []))
        for w in watchers:
            try:
                w("applied", obj)
            except Exception:
                self._log.exception("watcher failed kind=%s", kind)

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        with self._lock:
            obj = self._objs.get(kind, {}).pop(f"{namespace}/{name}", None)
            watchers = list(self._watchers.get(kind, []))
        if obj is None:
            raise KeyError(f"{kind} {namespace}/{name} not found")
        for w in watchers:
            try:
                w("deleted", obj)
            except Exception:
                self._log.exception("watcher failed kind=%s", kind)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        with self._lock:
            obj = self._objs.get(kind, {}).get(f"{namespace}/{name}")
        if obj is None:
            raise KeyError(f"{kind} {namespace}/{name} not found")
        return obj

    def list(self, kind: str) -> list[Any]:
        with self._lock:
            return list(self._objs.get(kind, {}).values())

    def watch(self, kind: str, fn: WatchFn) -> None:
        with self._lock:
            self._watchers.setdefault(kind, []).append(fn)
        # Replay existing objects (informer initial-sync semantics).
        for obj in self.list(kind):
            try:
                fn("applied", obj)
            except Exception:
                self._log.exception("watcher replay failed kind=%s", kind)

"""Operator reconcilers.

Reference analogs:
- Capture controller (pkg/controllers/operator/capture/controller.go:102):
  Reconcile → TranslateCaptureToJobs → create Jobs → update Capture status
  from Job completion (:142). Here "Jobs" are local worker threads running
  the CaptureManager on the nodes this process represents.
- Pod controller (operator/pod/pod_controller.go): publishes slim
  RetinaEndpoint objects — here, applies them into the identity cache.
- MetricsConfiguration controller
  (metricsconfiguration_controller.go:109): → MetricsModule.Reconcile.
- TracesConfiguration controller → TracesModule.
- Leader election (operator deployment.go): single-process here; the
  Operator is the leader by construction.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

from retina_tpu.capture.manager import CaptureManager
from retina_tpu.capture.translator import translate_capture_to_jobs
from retina_tpu.common import RetinaEndpoint, RetinaNode
from retina_tpu.crd.types import (
    Capture,
    MetricsConfiguration,
    TracesConfiguration,
    ValidationError,
)
from retina_tpu.log import logger
from retina_tpu.operator.store import CRDStore

KIND_CAPTURE = "Capture"
KIND_METRICS_CONF = "MetricsConfiguration"
KIND_TRACES_CONF = "TracesConfiguration"
KIND_ENDPOINT = "RetinaEndpoint"


class Operator:
    def __init__(
        self,
        store: CRDStore,
        cache: Any = None,
        metrics_module: Any = None,
        traces_module: Any = None,
        node_name: str = "local",
        nodes: Optional[list[RetinaNode]] = None,
        capture_manager: Optional[CaptureManager] = None,
        status_sink: Optional[Any] = None,
        leading: Optional[Any] = None,
        job_runner: Optional[Any] = None,
        cluster_nodes: Optional[Any] = None,
        storage_manager: Optional[Any] = None,
        secret_writer: Optional[Any] = None,
    ):
        """``status_sink(kind, obj)`` is called when an object's status
        settles — the kube backend passes KubeBridge.patch_status so
        status reaches the apiserver's status subresource
        (controller.go:142 updateCaptureStatusFromJobs analog).

        ``leading()`` gates side-effectful reconciles (captures): a
        follower replica watches but does not act (controller-runtime
        leader election analog, operator/cmd/root.go:21-39). Call
        :meth:`resync` when leadership is gained so objects applied
        while following get reconciled."""
        self._log = logger("operator")
        self.store = store
        self.cache = cache
        self.metrics_module = metrics_module
        self.traces_module = traces_module
        self.node_name = node_name
        self.nodes = nodes or [RetinaNode(name=node_name)]
        self.capture_manager = capture_manager or CaptureManager()
        self.status_sink = status_sink
        self.leading = leading or (lambda: True)
        # Remote execution (capture controller.go:102 creates batch/v1
        # Jobs per node): non-local CaptureJobs go through this runner
        # when present; without it they are skipped as before.
        self.job_runner = job_runner
        # Live cluster node inventory for capture translation (the kube
        # backend wires a node watcher); falls back to the static list.
        self.cluster_nodes = cluster_nodes
        # Managed capture storage (capture/managed.py; reference
        # controller.go:310-350): when a Capture names no output and a
        # manager is configured, the operator mints a write-only
        # container SAS. ``secret_writer(namespace, name, sas_url) ->
        # secret name`` stores it as a k8s Secret (kube mode); without
        # one the SAS rides in the spec directly (in-process mode, where
        # BlobOutput accepts a literal URL).
        self.storage_manager = storage_manager
        self.secret_writer = secret_writer
        # Bounded not-yet-synced deferrals per capture key.
        self._defers: dict[str, int] = {}
        self.max_defers = 24  # x5s = 2 min of inventory warm-up
        self._jobs: dict[str, threading.Thread] = {}
        self._jobs_lock = threading.Lock()

    def _sync_status(self, kind: str, obj: Any) -> None:
        if self.status_sink is not None:
            try:
                self.status_sink(kind, obj)
            except Exception:  # noqa: BLE001
                self._log.exception("status sink failed for %s/%s",
                                    kind, getattr(obj, "name", "?"))

    def start(self) -> None:
        """Register all watches (controller manager start analog)."""
        self.store.watch(KIND_CAPTURE, self._on_capture)
        self.store.watch(KIND_METRICS_CONF, self._on_metrics_conf)
        self.store.watch(KIND_TRACES_CONF, self._on_traces_conf)
        self.store.watch(KIND_ENDPOINT, self._on_endpoint)
        self._log.info("operator started (node=%s)", self.node_name)

    # -- capture reconcile (controller.go:102) -------------------------
    def resync(self) -> None:
        """Leadership-gained hook: reconcile every Pending capture, and
        fail captures stuck Running from a dead leader — their "jobs"
        were threads in that process, so nobody will ever complete them
        (unlike the reference, whose k8s Jobs outlive the operator)."""
        for cap in self.store.list(KIND_CAPTURE):
            if cap.status.phase == "Running":
                key = f"{cap.namespace}/{cap.name}"
                with self._jobs_lock:
                    mine = self._jobs.get(key)
                if mine is None or not mine.is_alive():
                    self._handle_orphan(cap)
                continue
            self._on_capture("applied", cap)

    def _handle_orphan(self, cap: Capture) -> None:
        """A Running capture with no live local thread: the old leader
        died. Its LOCAL jobs died with it, but any remote batch/v1 Jobs
        are still running on the cluster — adopt those instead of
        failing them (they'd otherwise complete invisibly)."""

        def settle(completed: int, failed: int,
                   artifacts: list[str], msg: str) -> None:
            cap.status.jobs_completed += completed
            cap.status.jobs_failed += failed
            cap.status.jobs_active = 0
            cap.status.artifacts.extend(artifacts)
            cap.status.message = msg
            cap.status.phase = (
                "Failed" if failed or not completed else "Completed"
            )
            self._sync_status(KIND_CAPTURE, cap)

        if self.job_runner is None:
            settle(0, cap.status.jobs_active, [],
                   "orphaned by leader failover; re-apply to retry")
            self._log.warning("capture %s orphaned by failover", cap.name)
            return

        orphaned = cap.status.jobs_active

        def adopt() -> None:
            res = self.job_runner.adopt(cap.name, cap.namespace)
            if res is None:
                settle(0, orphaned, [],
                       "orphaned by leader failover; re-apply to retry")
                return
            completed, failed, artifacts = res
            # The dead leader's LOCAL jobs have no batch/v1 Job to
            # adopt — whatever the adoption didn't account for was lost
            # with that process and counts as failed.
            lost = max(0, orphaned - completed - failed)
            self._log.info(
                "capture %s: adopted %d job(s) from dead leader "
                "(%d failed, %d lost local)", cap.name,
                completed + failed, failed, lost,
            )
            settle(completed, failed + lost, artifacts,
                   "adopted from failed-over leader"
                   + (f"; {lost} local job(s) lost with it" if lost
                      else ""))

        # Registered under the capture key like a normal job thread so a
        # leadership flap cannot start a second adoption (double
        # counting); _on_capture's dedupe and this share _jobs.
        t = threading.Thread(target=adopt, daemon=True,
                             name=f"adopt-{cap.name}")
        key = f"{cap.namespace}/{cap.name}"
        with self._jobs_lock:
            prev = self._jobs.get(key)
            if prev is not None and prev.is_alive():
                return  # adoption (or a real run) already in flight
            self._jobs[key] = t
        t.start()

    def _on_capture(self, event: str, cap: Capture) -> None:
        if event != "applied" or cap.status.phase not in ("Pending",):
            return
        if not self.leading():
            return  # follower: watch only; resync() runs these later
        # Dedupe: a watch reconnect can re-LIST an in-flight capture whose
        # apiserver copy still says Pending; don't start a duplicate job.
        key = f"{cap.namespace}/{cap.name}"
        with self._jobs_lock:
            prev = self._jobs.get(key)
            if prev is not None and prev.is_alive():
                return
        def defer(reason: str) -> bool:
            """Bounded retry while the node watcher warms up; returns
            False when the budget is spent (caller then Fails)."""
            n = self._defers.get(key, 0)
            if n >= self.max_defers:
                return False
            self._defers[key] = n + 1
            self._log.info("capture %s deferred (%d/%d): %s", cap.name,
                           n + 1, self.max_defers, reason)
            t = threading.Timer(
                5.0, lambda: self._on_capture("applied", cap))
            t.daemon = True
            t.start()
            return True

        # Managed storage: a Capture with NO output location gets a
        # provisioned container + write-only SAS before translation
        # (reference controller.go:310-350 creates the secret, sets
        # Spec.OutputConfiguration.BlobUpload, then creates jobs).
        out = cap.spec.output
        if self.storage_manager is not None and out.is_empty():
            try:
                sas = self.storage_manager.create_container_sas_url(
                    cap.namespace, cap.spec.duration_s
                )
                if self.secret_writer is not None:
                    out.blob_upload_secret = self.secret_writer(
                        cap.namespace, f"capture-blob-{cap.name}", sas
                    )
                else:
                    out.blob_upload_secret = sas
                self._sync_status(KIND_CAPTURE, cap)
            except Exception as e:  # provisioning failed: Fail loudly
                cap.status.phase = "Failed"
                cap.status.message = f"managed storage: {e}"
                self._log.warning(
                    "capture %s managed storage failed: %s", cap.name, e
                )
                self._sync_status(KIND_CAPTURE, cap)
                return

        try:
            pods = (
                [ep for ep in self.cache.index_label_map().values()]
                if self.cache else []
            )
            if self.cluster_nodes is not None:
                inventory = self.cluster_nodes()
                if not inventory:
                    # Node watcher not synced yet (operator just booted
                    # and the kube bridge replayed captures first).
                    if defer("node inventory empty"):
                        return
                    inventory = self.nodes  # spent: fail loudly below
            else:
                inventory = self.nodes
            jobs = translate_capture_to_jobs(cap, inventory, pods)
        except ValidationError as e:
            if ("unknown nodes" in str(e)
                    and self.cluster_nodes is not None
                    and defer(f"inventory may be partial: {e}")):
                # A mid-LIST inventory can be non-empty but incomplete;
                # real unknown nodes still Fail once the budget is spent.
                return
            cap.status.phase = "Failed"
            cap.status.message = str(e)
            self._log.warning("capture %s rejected: %s", cap.name, e)
            self._sync_status(KIND_CAPTURE, cap)
            return
        self._defers.pop(key, None)
        # With a job runner, only THIS process's node runs in-process —
        # every other node gets a batch/v1 Job. Without one, self.nodes
        # is "nodes this process represents" (single-process mode).
        our_nodes = (
            {self.node_name} if self.job_runner is not None
            else {n.name for n in self.nodes}
        )
        local = [j for j in jobs if j.node_name in our_nodes]
        # Remote nodes get batch/v1 Jobs through the runner
        # (controller.go:102); without a runner they are skipped, as in
        # the single-process deployments.
        remote = (
            [j for j in jobs if j.node_name not in our_nodes]
            if self.job_runner is not None else []
        )
        cap.status.phase = "Running"
        cap.status.jobs_active = len(local) + len(remote)
        self._log.info(
            "capture %s: %d job(s) (%d local, %d remote)", cap.name,
            len(jobs), len(local), len(remote),
        )
        # Publish Running immediately so backends see the in-flight phase
        # (and a watch echo of this write is a no-op, not a re-trigger).
        self._sync_status(KIND_CAPTURE, cap)

        def run_all() -> None:
            failed = 0

            def account(fn, job) -> None:
                nonlocal failed
                try:
                    cap.status.artifacts.extend(fn(job))
                    cap.status.jobs_completed += 1
                except Exception as e:  # noqa: BLE001
                    self._log.exception("capture job %s failed",
                                        job.job_name())
                    failed += 1
                    cap.status.jobs_failed += 1
                    cap.status.message = str(e)
                cap.status.jobs_active -= 1

            # Create EVERY remote Job first so the per-node capture
            # windows overlap (controller.go creates all Jobs in one
            # reconcile), then run local capture, then wait the remotes.
            # The run id scopes a future failover adoption to THIS
            # generation of Jobs.
            run_id = f"{int(time.time()):x}"
            created: list[tuple[str, Any]] = []
            for job in remote:
                try:
                    created.append(
                        (self.job_runner.create(job, run_id=run_id), job))
                except Exception as e:  # noqa: BLE001
                    self._log.exception("capture job create failed: %s",
                                        job.job_name())
                    failed += 1
                    cap.status.jobs_failed += 1
                    cap.status.message = str(e)
                    cap.status.jobs_active -= 1
            for job in local:
                account(self.capture_manager.run_job, job)
            for name, job in created:
                account(lambda j, n=name: self.job_runner.wait(n, j), job)
            cap.status.phase = "Failed" if failed else "Completed"
            self._sync_status(KIND_CAPTURE, cap)

        t = threading.Thread(
            target=run_all, name=f"capture-{cap.name}", daemon=True
        )
        with self._jobs_lock:
            self._jobs[key] = t
        t.start()

    def wait_capture(self, name: str, timeout: float = 120.0,
                     namespace: str = "default") -> None:
        """Block until the capture's job thread finishes.

        The apply -> watch -> reconcile hop is asynchronous, so the job
        thread may not EXIST yet when a caller that just applied the CR
        waits on it — poll for it up to the deadline instead of treating
        absence as completion (that race intermittently returned before
        the capture ran)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._jobs_lock:
                t = self._jobs.get(f"{namespace}/{name}")
            if t is not None:
                t.join(max(0.0, deadline - time.monotonic()))
                return
            if time.monotonic() >= deadline:
                return
            time.sleep(0.02)

    # -- config reconciles ---------------------------------------------
    def _on_metrics_conf(self, event: str, conf: MetricsConfiguration) -> None:
        if self.metrics_module is None:
            return
        if event == "applied":
            self.metrics_module.reconcile(conf)
        elif event == "deleted":
            self.metrics_module.reconcile(MetricsConfiguration.default())

    def _on_traces_conf(self, event: str, conf: TracesConfiguration) -> None:
        if self.traces_module is None:
            return
        if event == "deleted":
            # Deleting the CR must deactivate sampling (reconcile back
            # to the empty default), mirroring _on_metrics_conf.
            self.traces_module.reconcile(TracesConfiguration())
            return
        if event == "applied":
            self.traces_module.reconcile(conf)

    # -- endpoint publishing (pod_controller.go analog) ----------------
    def _on_endpoint(self, event: str, ep: RetinaEndpoint) -> None:
        if self.cache is None:
            return
        if event == "applied":
            self.cache.update_endpoint(ep)
        elif event == "deleted":
            self.cache.delete_endpoint(ep.key())

"""Lease-based leader election for the operator.

Reference analog: operator/cmd/root.go:21-39 — the standard operator
passes ``--enable-leader-election`` into controller-runtime, which
arbitrates a ``coordination.k8s.io/v1`` Lease so exactly one replica
reconciles; the cilium-crds cell configures the same via
LeaderElectionLeaseDuration/RenewDeadline (cells_linux.go:245).

Same protocol here on the stdlib client, with client-go's two key
robustness properties preserved:

- **Skew-safe expiry**: a follower never compares the remote renewTime
  against its own wall clock (clocks across replicas disagree). It times
  the lease from when it *locally observed* the current (holder,
  renewTime) pair, and only seizes after a full lease duration passes
  with no change — so a leader with a slow clock is not deposed early
  and two leaders cannot overlap.
- **Renew grace**: a leader keeps leadership through transient renew
  errors until the lease it last wrote would itself have expired
  (the renew-deadline), rather than flapping demote/promote on one
  connection reset. Losing the lease to another live holder demotes
  immediately.

Writes use resourceVersion preconditions so two candidates racing a
takeover cannot both win — the apiserver rejects the stale write with
409.
"""

from __future__ import annotations

import datetime
import json
import socket
import threading
import time
import urllib.error
from typing import Callable, Optional

from retina_tpu.log import logger
from retina_tpu.operator.kubeclient import KubeClient

COORD_V1 = "/apis/coordination.k8s.io/v1"


def _now() -> datetime.datetime:
    return datetime.datetime.now(datetime.timezone.utc)


def _fmt(ts: datetime.datetime) -> str:
    # k8s MicroTime format.
    return ts.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"


def _parse(ts: str) -> Optional[datetime.datetime]:
    if not ts:
        return None
    try:
        return datetime.datetime.strptime(
            ts.rstrip("Z"), "%Y-%m-%dT%H:%M:%S.%f"
        ).replace(tzinfo=datetime.timezone.utc)
    except ValueError:
        try:
            return datetime.datetime.strptime(
                ts.rstrip("Z"), "%Y-%m-%dT%H:%M:%S"
            ).replace(tzinfo=datetime.timezone.utc)
        except ValueError:
            return None


class LeaderElector:
    """Acquire/renew a Lease; exactly one identity leads at a time."""

    def __init__(
        self,
        client: KubeClient,
        name: str = "retina-tpu-operator",
        namespace: str = "kube-system",
        identity: str = "",
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        self._log = logger("leaderelection")
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity or f"{socket.gethostname()}-{id(self):x}"
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._leading = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Skew-safe follower state: the (holder, renewTime) pair we last
        # saw and WHEN WE saw it (local monotonic clock).
        self._observed: Optional[tuple[str, str]] = None
        self._observed_at = 0.0
        # Renew grace: when our own last successful write happened.
        self._last_write_ok = 0.0
        self._err_streak = 0

    # -- REST ----------------------------------------------------------
    def _url(self, suffix: str = "") -> str:
        return self.client.url(COORD_V1, "leases",
                               namespace=self.namespace, suffix=suffix)

    def _get_lease(self) -> Optional[dict]:
        """Returns the lease, None for 404, raises on other errors."""
        try:
            with self.client.request(self._url(f"/{self.name}")) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def _write_lease(self, lease: dict, create: bool) -> bool:
        """True on success; False when another writer won (409/404 on
        create); raises on auth/transport errors so the caller can tell
        'lost the race' from 'cluster problem'."""
        body = json.dumps(lease).encode()
        try:
            if create:
                self.client.request(self._url(), method="POST",
                                    body=body).close()
            else:
                self.client.request(self._url(f"/{self.name}"),
                                    method="PUT", body=body).close()
            self._last_write_ok = time.monotonic()
            return True
        except urllib.error.HTTPError as e:
            if e.code in (409, 404):
                self._log.debug("lease write lost the race (%d)", e.code)
                return False
            raise

    # -- election ------------------------------------------------------
    def try_acquire_or_renew(self) -> bool:
        """One election round; returns whether we lead afterwards."""
        lease = self._get_lease()
        now = _now()
        if lease is None:
            new = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name,
                             "namespace": self.namespace},
                "spec": {
                    "holderIdentity": self.identity,
                    # k8s field is integer seconds; 0 would mean
                    # instantly-expired, so clamp to >=1.
                    "leaseDurationSeconds": max(
                        1, int(self.lease_duration_s)),
                    "acquireTime": _fmt(now),
                    "renewTime": _fmt(now),
                    "leaseTransitions": 0,
                },
            }
            return self._write_lease(new, create=True)

        spec = lease.get("spec", {}) or {}
        holder = spec.get("holderIdentity", "")
        duration = float(spec.get("leaseDurationSeconds",
                                  self.lease_duration_s))
        if holder == self.identity:
            spec["renewTime"] = _fmt(now)
        elif holder:
            # Skew-safe expiry: never trust the remote timestamp against
            # our wall clock. Time the (holder, renewTime) pair on OUR
            # monotonic clock from first observation; seize only after a
            # full duration with no renewal observed.
            key = (holder, spec.get("renewTime", ""))
            mono = time.monotonic()
            if key != self._observed:
                self._observed = key
                self._observed_at = mono
                return False  # freshly observed: not ours this round
            if mono - self._observed_at <= duration:
                return False  # holder's lease still live by our watch
            self._take_over(spec, now)
        else:
            # Empty holder = gracefully released.
            self._take_over(spec, now)
        lease["spec"] = spec
        # resourceVersion rides along: a concurrent takeover bumps it and
        # our stale PUT is rejected with 409 -> we did NOT win.
        return self._write_lease(lease, create=False)

    def _take_over(self, spec: dict, now: datetime.datetime) -> None:
        spec["holderIdentity"] = self.identity
        spec["acquireTime"] = _fmt(now)
        spec["renewTime"] = _fmt(now)
        spec["leaseDurationSeconds"] = max(1, int(self.lease_duration_s))
        spec["leaseTransitions"] = int(spec.get("leaseTransitions", 0)) + 1

    def _set_leading(self, leading: bool) -> None:
        if leading == self._leading:
            return
        self._leading = leading
        self._log.info("%s leading (identity=%s)",
                       "started" if leading else "stopped", self.identity)
        cb = self.on_started_leading if leading else self.on_stopped_leading
        if cb is not None:
            try:
                cb()
            except Exception:  # noqa: BLE001
                self._log.exception("leader transition callback failed")

    def is_leader(self) -> bool:
        return self._leading

    # -- lifecycle -----------------------------------------------------
    def run_once(self) -> None:
        try:
            self._set_leading(self.try_acquire_or_renew())
            self._err_streak = 0
        except Exception as e:  # noqa: BLE001 — election never kills op
            self._err_streak += 1
            level = (self._log.warning if self._err_streak >= 3
                     else self._log.debug)
            level("election round failed (streak %d): %s: %s",
                  self._err_streak, type(e).__name__, e)
            if self._leading and (
                    time.monotonic() - self._last_write_ok
                    <= self.lease_duration_s):
                # Renew grace: the lease we wrote is still live; one
                # transient error must not flap leadership.
                return
            self._set_leading(False)

    def start(self) -> None:
        def loop() -> None:
            while not self._stop.is_set():
                self.run_once()
                self._stop.wait(self.renew_period_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="leaderelection")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
        if self._leading:
            # Graceful release: zero the holder so a peer takes over
            # immediately instead of waiting out the lease.
            try:
                lease = self._get_lease()
                if lease is not None and (
                        lease.get("spec", {}).get("holderIdentity")
                        == self.identity):
                    lease["spec"]["holderIdentity"] = ""
                    self._write_lease(lease, create=False)
            except Exception as e:  # noqa: BLE001 — best effort
                self._log.warning("lease release failed: %s", e)
            self._set_leading(False)

"""Minimal kube-apiserver REST client on the standard library.

The reference talks to the apiserver through client-go informers
(pkg/k8s/watcher_linux.go, controller-runtime managers); this image has
no ``kubernetes`` package, so the same REST contract — kubeconfig auth,
LIST, chunked WATCH with resourceVersion resumption, subresource PATCH —
is implemented directly on :mod:`urllib`. Shared by the CR bridge
(:class:`~retina_tpu.operator.bridge.KubeBridge`) and the core/v1
identity watcher (:class:`~retina_tpu.operator.kubewatch.CoreWatcher`).
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import threading
import urllib.request
from typing import Any, Callable, Optional

import yaml


# In-cluster service-account paths (what client-go's rest.InClusterConfig
# reads when a pod runs with a serviceAccountName).
SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_available(sa_dir: str = SA_DIR) -> bool:
    return bool(os.environ.get("KUBERNETES_SERVICE_HOST")) and os.path.exists(
        os.path.join(sa_dir, "token")
    )


class KubeClient:
    """kubeconfig- or service-account-authenticated REST to one apiserver.

    ``kubeconfig=""`` selects in-cluster config (the deployment path: the
    daemonset runs with a service account and no kubeconfig file), reading
    KUBERNETES_SERVICE_HOST/PORT and the mounted SA token + CA.
    """

    def __init__(self, kubeconfig: str = "", sa_dir: str = SA_DIR):
        if kubeconfig:
            self._load_kubeconfig(kubeconfig)
        elif in_cluster_available(sa_dir):
            self._load_in_cluster(sa_dir)
        else:
            raise ValueError(
                "no kubeconfig given and not running in-cluster "
                "(KUBERNETES_SERVICE_HOST unset or no service-account token)"
            )

    def _load_in_cluster(self, sa_dir: str) -> None:
        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.server = f"https://{host}:{port}"
        with open(os.path.join(sa_dir, "token")) as fh:
            self.token = fh.read().strip()
        self._ssl_ctx = ssl.create_default_context()
        ca = os.path.join(sa_dir, "ca.crt")
        if os.path.exists(ca):
            self._ssl_ctx.load_verify_locations(cafile=ca)

    # -- kubeconfig ----------------------------------------------------
    def _load_kubeconfig(self, path: str) -> None:
        with open(path) as fh:
            kc = yaml.safe_load(fh) or {}
        clusters = kc.get("clusters") or []
        if not clusters:
            raise ValueError(f"kubeconfig {path}: no clusters defined")
        contexts = kc.get("contexts") or []
        ctx_name = kc.get("current-context", "")
        ctx = next(
            (c.get("context", {}) for c in contexts
             if c.get("name") == ctx_name),
            contexts[0].get("context", {}) if contexts else {},
        )
        want_cluster = ctx.get("cluster", clusters[0].get("name"))
        cluster = next(
            (c["cluster"] for c in clusters
             if c.get("name") == want_cluster), None,
        )
        if cluster is None:
            raise ValueError(
                f"kubeconfig {path}: context references unknown cluster "
                f"{want_cluster!r}"
            )
        users = kc.get("users") or []
        user = next(
            (u.get("user", {}) for u in users
             if u.get("name") == ctx.get("user")),
            users[0].get("user", {}) if users else {},
        )
        if not cluster.get("server"):
            raise ValueError(f"kubeconfig {path}: cluster has no server URL")
        self.server = cluster["server"].rstrip("/")
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.server.startswith("https"):
            self._ssl_ctx = ssl.create_default_context()
            ca_data = cluster.get("certificate-authority-data")
            ca_file = cluster.get("certificate-authority")
            if ca_data:
                self._ssl_ctx.load_verify_locations(
                    cadata=base64.b64decode(ca_data).decode()
                )
            elif ca_file:
                self._ssl_ctx.load_verify_locations(cafile=ca_file)
            if cluster.get("insecure-skip-tls-verify"):
                self._ssl_ctx.check_hostname = False
                self._ssl_ctx.verify_mode = ssl.CERT_NONE
            cert_data = user.get("client-certificate-data")
            key_data = user.get("client-key-data")
            if cert_data and key_data:
                # load_cert_chain needs files; materialize with 0600.
                fd, certpath = tempfile.mkstemp(suffix=".pem")
                with os.fdopen(fd, "wb") as fh:
                    fh.write(base64.b64decode(cert_data))
                    fh.write(b"\n")
                    fh.write(base64.b64decode(key_data))
                self._ssl_ctx.load_cert_chain(certpath)
                os.unlink(certpath)
            elif user.get("client-certificate"):
                self._ssl_ctx.load_cert_chain(
                    user["client-certificate"], user.get("client-key")
                )
        self.token = user.get("token", "")

    # -- REST ----------------------------------------------------------
    def url(self, api_base: str, plural: str, namespace: str = "",
            suffix: str = "", query: str = "") -> str:
        """``api_base`` is e.g. ``/api/v1`` or ``/apis/retina.sh/v1alpha1``."""
        ns = f"/namespaces/{namespace}" if namespace else ""
        u = f"{self.server}{api_base}{ns}/{plural}{suffix}"
        return u + (f"?{query}" if query else "")

    def request(self, url: str, method: str = "GET",
                body: bytes | None = None,
                content_type: str = "application/json",
                timeout: float = 300):
        req = urllib.request.Request(url, data=body, method=method)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        if body is not None:
            req.add_header("Content-Type", content_type)
        return urllib.request.urlopen(req, context=self._ssl_ctx,
                                      timeout=timeout)

    # -- list + watch --------------------------------------------------
    def list_watch(
        self,
        api_base: str,
        plural: str,
        on_event: Callable[[str, dict], None],
        stop: threading.Event,
        namespace: str = "",
        retry_s: float = 2.0,
        log: Any = None,
        on_sync: Optional[Callable[[list[dict]], None]] = None,
        watch_timeout_s: int = 240,
    ) -> None:
        """The client-go informer loop, minus the local store.

        LIST once, then WATCH with resourceVersion continuation: the
        server closes the stream after ``watch_timeout_s``
        (``timeoutSeconds``) and the loop re-WATCHes from the last seen
        resourceVersion WITHOUT re-listing — bookmarks keep the rv fresh
        on quiet streams, so an idle cluster costs one tiny request per
        cycle, not a full collection LIST. A connection failure or an
        ERROR event (410 Gone) falls back to a fresh LIST.

        ``on_sync(metadatas)`` fires after every LIST with the metadata of
        every listed item, so the consumer can delete objects that
        vanished while the watch was down (informer resync semantics —
        an upsert stream cannot express a missed delete).
        """
        rv = ""
        need_list = True
        while not stop.is_set():
            try:
                if need_list:
                    with self.request(self.url(api_base, plural,
                                               namespace=namespace)) as resp:
                        body = json.load(resp)
                    rv = body.get("metadata", {}).get("resourceVersion", "")
                    items = body.get("items", [])
                    for item in items:
                        on_event("ADDED", item)
                    if on_sync is not None:
                        on_sync([it.get("metadata", {}) or {}
                                 for it in items])
                    need_list = False
                q = (
                    "watch=true&allowWatchBookmarks=true"
                    f"&timeoutSeconds={watch_timeout_s}"
                    + (f"&resourceVersion={rv}" if rv else "")
                )
                with self.request(
                    self.url(api_base, plural, namespace=namespace, query=q),
                    timeout=watch_timeout_s + 60,
                ) as stream:
                    for line in stream:
                        if stop.is_set():
                            return
                        if not line.strip():
                            continue
                        ev = json.loads(line)
                        etype = ev.get("type", "")
                        obj = ev.get("object", {}) or {}
                        if etype == "ERROR":
                            # e.g. 410 Gone: rv too old — full resync.
                            need_list = True
                            rv = ""
                            break
                        new_rv = (obj.get("metadata", {}) or {}).get(
                            "resourceVersion", "")
                        if new_rv:
                            rv = new_rv
                        if etype == "BOOKMARK":
                            continue
                        on_event(etype, obj)
                # Clean server-side close: loop re-watches from rv with no
                # LIST and no backoff.
                continue
            except Exception as e:  # noqa: BLE001 — watch never dies
                if stop.is_set():
                    return
                need_list = True
                if log is not None:
                    log.warning(
                        "%s list/watch failed (%s: %s); retrying in %.1fs",
                        plural, type(e).__name__, e, retry_s,
                    )
            stop.wait(retry_s)

"""CRD self-registration.

Reference analog: deploy/standard/registercrd.go — the operator embeds
its CRD YAMLs and applies them at startup when ``InstallCRDs`` is set
(operator/cmd/standard/deployment.go:149), so a bare cluster needs no
separate install step. Here the manifests are GENERATED from this module
(the container ships no YAML files); ``deploy/manifests/crds.yaml`` is
the rendered copy for ``kubectl apply`` flows, and a test keeps the two
identical.
"""

from __future__ import annotations

import json
import urllib.error
from typing import Any

from retina_tpu.log import logger
from retina_tpu.operator.kubeclient import KubeClient

APIEXT_V1 = "/apis/apiextensions.k8s.io/v1"

# kind -> (plural, spec description, status description, printer columns)
_CRDS: dict[str, tuple[str, str, str, list[dict]]] = {
    "Capture": (
        "captures",
        "Capture spec (crd/types.py CaptureSpec): captureTarget "
        "(nodeSelector/nodeNames XOR podSelector/namespaceSelector), "
        "outputConfiguration (hostPath / persistentVolumeClaim / "
        "blobUpload / s3Upload), duration (seconds, <= 3600), "
        "tcpdumpFilter.",
        "Written by the operator via the status subresource: phase "
        "(Pending|Running|Completed|Failed), jobs_active, "
        "jobs_completed, jobs_failed, message, artifacts.",
        [
            {"name": "Phase", "type": "string",
             "jsonPath": ".status.phase"},
            {"name": "Completed", "type": "integer",
             "jsonPath": ".status.jobs_completed"},
            {"name": "Age", "type": "date",
             "jsonPath": ".metadata.creationTimestamp"},
        ],
    ),
    "MetricsConfiguration": (
        "metricsconfigurations",
        "MetricsSpec (crd/types.py): contextOptions (metricName + "
        "sourceLabels/destinationLabels/additionalLabels), "
        "namespaces.include XOR namespaces.exclude.",
        "",
        [],
    ),
    "TracesConfiguration": ("tracesconfigurations", "", "", []),
}


def crd_manifests() -> list[dict[str, Any]]:
    """The CustomResourceDefinition docs for every retina.sh kind."""
    out = []
    for kind, (plural, spec_desc, status_desc, cols) in _CRDS.items():
        def prop(desc: str) -> dict:
            p: dict[str, Any] = {
                "type": "object",
                "x-kubernetes-preserve-unknown-fields": True,
            }
            if desc:
                p["description"] = desc
            return p

        version: dict[str, Any] = {
            "name": "v1alpha1",
            "served": True,
            "storage": True,
            "subresources": {"status": {}},
            "schema": {
                "openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": prop(spec_desc),
                        "status": prop(status_desc),
                    },
                },
            },
        }
        if cols:
            version["additionalPrinterColumns"] = cols
        out.append({
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": f"{plural}.retina.sh"},
            "spec": {
                "group": "retina.sh",
                "names": {
                    "categories": ["retina"],
                    "kind": kind,
                    "listKind": f"{kind}List",
                    "plural": plural,
                    "singular": kind.lower(),
                },
                "scope": "Namespaced",
                "versions": [version],
            },
        })
    return out


def render(path: str = "deploy/manifests/crds.yaml") -> None:
    """Regenerate the rendered YAML copy of the manifests."""
    import yaml

    header = (
        "# CustomResourceDefinitions for the retina.sh API group — what "
        "the\n# operator's kube backend (retina_tpu/operator/bridge.py "
        "KubeBridge) and\n# kubectl-retina work against. GENERATED from\n"
        "# retina_tpu/operator/crdinstall.py (the operator can also "
        "self-install\n# these with --install-crds, the registercrd.go "
        "analog); a test keeps\n# this file and the code in sync. "
        "Regenerate with:\n#   python -c \"from "
        "retina_tpu.operator.crdinstall import render; render()\"\n"
    )
    body = "".join(
        "---\n" + yaml.safe_dump(d, sort_keys=False)
        for d in crd_manifests()
    )
    with open(path, "w") as fh:
        fh.write(header + body)


def install_crds(client: KubeClient, timeout: float = 30.0) -> int:
    """POST each CRD; on AlreadyExists, PUT the current manifest over it
    so upgrades take effect (registercrd.go applies, not create-only).
    Best effort with a short timeout — a black-holed apiserver must not
    stall operator startup. Returns created+updated count."""
    log = logger("crdinstall")
    applied = 0
    base = client.url(APIEXT_V1, "customresourcedefinitions")
    for doc in crd_manifests():
        name = doc["metadata"]["name"]
        try:
            client.request(base, method="POST",
                           body=json.dumps(doc).encode(),
                           timeout=timeout).close()
            applied += 1
            log.info("installed CRD %s", name)
        except urllib.error.HTTPError as e:
            if e.code != 409:
                log.warning("CRD %s install failed: HTTP %d",
                            name, e.code)
                continue
            try:
                applied += self_update(client, doc, timeout)
            except Exception as e2:  # noqa: BLE001
                log.warning("CRD %s update failed: %s", name, e2)
        except Exception as e:  # noqa: BLE001 — install is best effort
            log.warning("CRD %s install failed: %s", name, e)
    return applied


def self_update(client: KubeClient, doc: dict, timeout: float) -> int:
    """Update an existing CRD to the current manifest (upgrade path).
    Returns 1 when a PUT was issued, 0 when already current."""
    log = logger("crdinstall")
    name = doc["metadata"]["name"]
    url = client.url(APIEXT_V1, "customresourcedefinitions",
                     suffix=f"/{name}")
    with client.request(url, timeout=timeout) as r:
        cur = json.load(r)
    if cur.get("spec") == doc["spec"]:
        log.debug("CRD %s already current", name)
        return 0
    merged = dict(doc)
    merged["metadata"] = {
        **doc["metadata"],
        "resourceVersion": cur["metadata"]["resourceVersion"],
    }
    client.request(url, method="PUT",
                   body=json.dumps(merged).encode(),
                   timeout=timeout).close()
    log.info("updated CRD %s", name)
    return 1

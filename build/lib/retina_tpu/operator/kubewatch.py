"""Core/v1 identity watchers: pods, services, nodes → identity cache.

Reference analogs:
- pkg/k8s/watcher_linux.go — the agent's apiserver watcher layer.
- pkg/controllers/daemon/pod/controller.go:38-86 — Pod → slim
  RetinaEndpoint into the cache; host-network pods ignored; pods without
  an IP skipped; deletion (or deletionTimestamp) removes the endpoint.
- pkg/controllers/daemon/service/controller.go — Service → RetinaSvc.
- pkg/controllers/daemon/node/controller.go — Node → RetinaNode.

Design: one list+watch thread per resource over the shared
:class:`~retina_tpu.operator.kubeclient.KubeClient`. Translation is pure
(`pod_to_endpoint` etc.) so it is testable without an apiserver; events
land as upserts/deletes on :class:`~retina_tpu.controllers.cache.Cache`,
which assigns the dense pod indexes feeding the device IdentityMap — so a
pod appearing in the cluster becomes a joinable identity on-device after
the next identity reconcile, exactly like a CRD-store endpoint apply.
"""

from __future__ import annotations

import threading
from typing import Optional

from retina_tpu.common import (
    POD_ANNOTATION,
    POD_ANNOTATION_VALUE,
    RetinaEndpoint,
    RetinaNode,
    RetinaSvc,
)
from retina_tpu.log import logger
from retina_tpu.operator.kubeclient import KubeClient

CORE_V1 = "/api/v1"


# -- pure translations (controller.go Reconcile bodies) -----------------
def pod_to_endpoint(doc: dict) -> Optional[RetinaEndpoint]:
    """Pod → RetinaEndpoint; None = ignore (host-network or no IP yet,
    pod/controller.go:61-77)."""
    spec = doc.get("spec", {}) or {}
    status = doc.get("status", {}) or {}
    meta = doc.get("metadata", {}) or {}
    if spec.get("hostNetwork"):
        return None
    ips = tuple(
        e["ip"] for e in status.get("podIPs") or []
        if e.get("ip")
    ) or ((status.get("podIP"),) if status.get("podIP") else ())
    if not ips:
        return None
    return RetinaEndpoint(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        ips=ips,
        labels=tuple(sorted((meta.get("labels") or {}).items())),
        owner_refs=tuple(
            (r.get("kind", ""), r.get("name", ""))
            for r in meta.get("ownerReferences") or []
        ),
        containers=tuple(
            c.get("name", "") for c in spec.get("containers") or []
        ),
        annotations=tuple(sorted((meta.get("annotations") or {}).items())),
        node=spec.get("nodeName", ""),
    )


def service_to_svc(doc: dict) -> RetinaSvc:
    meta = doc.get("metadata", {}) or {}
    spec = doc.get("spec", {}) or {}
    status = doc.get("status", {}) or {}
    lb_ingress = (status.get("loadBalancer") or {}).get("ingress") or []
    return RetinaSvc(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        cluster_ip=(
            "" if spec.get("clusterIP") in (None, "None")
            else spec.get("clusterIP", "")
        ),
        lb_ip=(lb_ingress[0].get("ip", "") if lb_ingress else ""),
        selector=tuple(sorted((spec.get("selector") or {}).items())),
    )


def node_to_node(doc: dict) -> RetinaNode:
    meta = doc.get("metadata", {}) or {}
    status = doc.get("status", {}) or {}
    internal = next(
        (a.get("address", "") for a in status.get("addresses") or []
         if a.get("type") == "InternalIP"),
        "",
    )
    labels = meta.get("labels") or {}
    return RetinaNode(
        name=meta.get("name", ""),
        ip=internal,
        zone=labels.get("topology.kubernetes.io/zone", ""),
    )


class CoreWatcher:
    """Three list+watch loops feeding the identity cache.

    When active, this watcher OWNS pod/service identity in the cache:
    post-LIST resync deletes cache entries absent from the apiserver, so
    don't feed the same cache from the CRD-store RetinaEndpoint path
    concurrently (the two sources would fight; pick one per deployment,
    as the reference does with its enable-retina-endpoint switch).
    """

    def __init__(self, cache, kubeconfig: str, namespace: str = "",
                 retry_s: float = 2.0, include_pods: bool = True,
                 include_services: bool = True,
                 include_nodes: bool = True,
                 include_namespaces: bool = False,
                 on_pods_synced=None):
        """``include_pods=False`` watches only services+nodes — used when
        pod identity comes from elsewhere (CiliumEndpoints); a pods-only
        watcher (others False) backs the operator's CEP publisher.
        ``include_namespaces`` adds the annotated-namespace watch (the
        enable_annotations opt-in path). ``on_pods_synced()`` fires after
        each pod LIST resync — the publisher's restart GC hook."""
        self._log = logger("kubewatch")
        self.cache = cache
        self.namespace = namespace  # "" = cluster-wide (pods/services)
        self.retry_s = retry_s
        self.include_pods = include_pods
        self.include_services = include_services
        self.include_nodes = include_nodes
        self.include_namespaces = include_namespaces
        self.on_pods_synced = on_pods_synced
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.client = KubeClient(kubeconfig)

    # -- event handlers ------------------------------------------------
    def _on_pod(self, event: str, doc: dict) -> None:
        meta = doc.get("metadata", {}) or {}
        key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        deleting = (
            event == "DELETED" or meta.get("deletionTimestamp") is not None
        )
        if deleting:
            self.cache.delete_endpoint(key)
            return
        ep = pod_to_endpoint(doc)
        if ep is not None:
            self.cache.update_endpoint(ep)

    def _on_service(self, event: str, doc: dict) -> None:
        svc = service_to_svc(doc)
        if event == "DELETED":
            self.cache.delete_service(svc.key())
        else:
            self.cache.update_service(svc)

    def _on_node(self, event: str, doc: dict) -> None:
        # Node removal keeps the last-known entry (reference cache has no
        # node delete either); stale nodes age out with the cluster.
        if event != "DELETED":
            self.cache.update_node(node_to_node(doc))

    def _on_namespace(self, event: str, doc: dict) -> None:
        """namespace_controller.go:54-62: the retina.sh=observe
        annotation opts a whole namespace into pod-level metrics."""
        meta = doc.get("metadata", {}) or {}
        name = meta.get("name", "")
        if not name:
            return
        annotated = (
            event != "DELETED"
            and meta.get("deletionTimestamp") is None
            and (meta.get("annotations") or {}).get(POD_ANNOTATION)
            == POD_ANNOTATION_VALUE
        )
        self.cache.set_annotated_namespace(name, annotated)

    # -- resync (informer semantics): a re-LIST after a dropped watch
    # must delete objects that vanished while disconnected, or stale
    # endpoints pin dense pod indexes forever.
    @staticmethod
    def _keys(metas: list[dict]) -> set[str]:
        return {
            f"{m.get('namespace', 'default')}/{m.get('name', '')}"
            for m in metas
        }

    def _sync_pods(self, metas: list[dict]) -> None:
        listed = self._keys(metas)
        for key in self.cache.list_endpoint_keys():
            if key not in listed:
                self.cache.delete_endpoint(key)
        if self.on_pods_synced is not None:
            self.on_pods_synced()

    def _sync_services(self, metas: list[dict]) -> None:
        listed = self._keys(metas)
        for key in self.cache.list_service_keys():
            if key not in listed:
                self.cache.delete_service(key)

    def _sync_namespaces(self, metas: list[dict]) -> None:
        annotated = {
            m.get("name", "") for m in metas
            if (m.get("annotations") or {}).get(POD_ANNOTATION)
            == POD_ANNOTATION_VALUE
        }
        for ns in self.cache.annotated_namespaces() - annotated:
            self.cache.set_annotated_namespace(ns, False)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        plans = []
        if self.include_pods:
            plans.append(("pods", self._on_pod, self.namespace,
                          self._sync_pods))
        if self.include_services:
            plans.append(("services", self._on_service, self.namespace,
                          self._sync_services))
        if self.include_nodes:
            plans.append(("nodes", self._on_node, "", None))  # cluster-scoped
        if self.include_namespaces:
            plans.append(("namespaces", self._on_namespace, "",
                          self._sync_namespaces))
        for plural, handler, ns, sync in plans:
            t = threading.Thread(
                target=self.client.list_watch,
                args=(CORE_V1, plural),
                kwargs={
                    "on_event": handler,
                    "stop": self._stop,
                    "namespace": ns,
                    "retry_s": self.retry_s,
                    "log": self._log,
                    "on_sync": sync,
                },
                name=f"kubewatch-{plural}", daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._log.info("core/v1 watchers (%s) at %s",
                       ",".join(p[0] for p in plans), self.client.server)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(2.0)

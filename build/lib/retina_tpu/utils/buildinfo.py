"""Build metadata (reference internal/buildinfo: ldflags-injected vars)."""

VERSION = "0.1.0"
APP_NAME = "retina-tpu"
USER_AGENT = f"{APP_NAME}/{VERSION}"

"""Monotonic→wall clock conversion (reference internal/ktime).

Event sources stamp records with a monotonic nanosecond clock (the eBPF
analog is bpf_ktime_get_ns); exporters want wall time. The boot offset is
computed once per process, as in the reference (used at
packetparser_linux.go:585).
"""

from __future__ import annotations

import time

_offset_ns: int | None = None


def boot_offset_ns() -> int:
    """wall_ns - monotonic_ns, sampled once."""
    global _offset_ns
    if _offset_ns is None:
        _offset_ns = time.time_ns() - time.monotonic_ns()
    return _offset_ns


def monotonic_to_wall_ns(mono_ns: int) -> int:
    return mono_ns + boot_offset_ns()

"""helmlite: render Helm charts without a helm binary.

Reference analog: the reference ships a values-driven Helm chart
(`deploy/standard/manifests/controller/helm/retina/templates/*`) and
drives installs through the helm SDK (`deploy/standard/*.go`). This
framework's chart (deploy/helm/retina-tpu) is a REAL chart — installable
with stock `helm install` — but the repo also needs to render it without
helm: the CLI's ``deploy render`` verb (air-gapped clusters, kubectl
apply pipelines) and the manifest-coherence tests both run in
environments where only Python exists.

So this module implements the Go-template subset the chart restricts
itself to:

- actions with whitespace control: ``{{ expr }}``, ``{{- expr -}}``
- data paths: ``.Values.a.b``, ``.Release.Name/Namespace``,
  ``.Chart.Name/Version``
- literals: double-quoted strings, ints, true/false
- pipelines: ``expr | fn arg ...`` with quote, toYaml, indent N,
  nindent N, default X
- control flow: ``if`` / ``else`` / ``end`` (Go truthiness: empty
  string/list/map, 0, false, nil are falsy)
- comments: ``{{/* ... */}}``

Anything outside the subset raises — a template drifting beyond it
should fail tests loudly, not render wrongly.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

import yaml

_ACTION = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


class HelmliteError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Expression evaluation


def _truthy(v: Any) -> bool:
    return not (v is None or v is False or v == "" or v == [] or v == {} or v == 0)


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _indent(n: int, s: str) -> str:
    pad = " " * n
    return "\n".join(pad + line if line else line for line in str(s).split("\n"))


def _fmt(v: Any) -> str:
    """Go template default formatting for interpolated values."""
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    return str(v)


_TOKEN = re.compile(r'"(?:[^"\\]|\\.)*"|\S+')


def _eval_atom(tok: str, ctx: dict[str, Any]) -> Any:
    if tok.startswith('"'):
        return json.loads(tok)
    if tok in ("true", "false"):
        return tok == "true"
    if re.fullmatch(r"-?\d+", tok):
        return int(tok)
    if re.fullmatch(r"-?\d+\.\d+", tok):
        return float(tok)
    if tok.startswith("."):
        cur: Any = ctx
        for part in tok[1:].split("."):
            if not part:
                raise HelmliteError(f"bad path {tok!r}")
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                return None
        return cur
    raise HelmliteError(f"unsupported token {tok!r}")


def _apply_fn(name: str, args: list[Any]) -> Any:
    if name == "quote":
        (v,) = args
        return json.dumps("" if v is None else str(_fmt(v)))
    if name == "toYaml":
        (v,) = args
        return _to_yaml(v)
    if name == "indent":
        n, v = args
        return _indent(int(n), v)
    if name == "nindent":
        n, v = args
        return "\n" + _indent(int(n), v)
    if name == "default":
        dflt, v = args
        return v if _truthy(v) else dflt
    raise HelmliteError(f"unsupported function {name!r}")


_FUNCTIONS = ("quote", "toYaml", "indent", "nindent", "default")


def eval_expr(expr: str, ctx: dict[str, Any]) -> Any:
    """Evaluate one pipeline expression against the context."""
    stages = [s.strip() for s in expr.split("|")]
    value: Any = None
    for i, stage in enumerate(stages):
        toks = _TOKEN.findall(stage)
        if not toks:
            raise HelmliteError(f"empty pipeline stage in {expr!r}")
        if toks[0] in _FUNCTIONS:
            args = [_eval_atom(t, ctx) for t in toks[1:]]
            if i > 0:
                args.append(value)
            value = _apply_fn(toks[0], args)
        else:
            if len(toks) != 1 or i > 0:
                raise HelmliteError(f"unsupported expression {stage!r}")
            value = _eval_atom(toks[0], ctx)
    return value


# ---------------------------------------------------------------------------
# Template parsing/rendering


def render(template: str, ctx: dict[str, Any]) -> str:
    """Render one template body with Go-template whitespace semantics."""
    # Tokenize into (literal, action) runs with trim flags applied.
    pos = 0
    parts: list[tuple[str, str]] = []  # ("lit", text) | ("act", body)
    for mobj in _ACTION.finditer(template):
        lit = template[pos : mobj.start()]
        if mobj.group(1) == "-":
            lit = re.sub(r"[ \t]*\n?[ \t]*$", "", lit)
        parts.append(("lit", lit))
        parts.append(("act", mobj.group(2)))
        pos = mobj.end()
        if mobj.group(3) == "-":
            rest = template[pos:]
            trimmed = re.sub(r"^[ \t]*\n?", "", rest, count=1)
            pos += len(rest) - len(trimmed)
    parts.append(("lit", template[pos:]))

    out: list[str] = []
    # Stack of (emitting_before, branch_taken, in_else)
    stack: list[tuple[bool, bool, bool]] = []
    emitting = True
    for kind, text in parts:
        if kind == "lit":
            if emitting:
                out.append(text)
            continue
        body = text.strip()
        if body.startswith("/*"):
            continue
        if body.startswith("if "):
            cond = emitting and _truthy(eval_expr(body[3:], ctx))
            stack.append((emitting, cond, False))
            emitting = emitting and cond
        elif body == "else":
            if not stack:
                raise HelmliteError("else without if")
            outer, taken, in_else = stack[-1]
            if in_else:
                raise HelmliteError("double else")
            stack[-1] = (outer, taken, True)
            emitting = outer and not taken
        elif body == "end":
            if not stack:
                raise HelmliteError("end without if")
            emitting = stack.pop()[0]
        else:
            if emitting:
                out.append(_fmt(eval_expr(body, ctx)))
    if stack:
        raise HelmliteError("unclosed if")
    return "".join(out)


# ---------------------------------------------------------------------------
# Chart-level API


def _deep_merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _set_path(values: dict, dotted: str, raw: str) -> None:
    cur = values
    parts = dotted.split(".")
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = yaml.safe_load(raw)


def render_chart(
    chart_dir: str,
    release_name: str = "retina-tpu",
    namespace: str | None = None,
    values_files: list[str] | None = None,
    set_values: list[str] | None = None,
) -> dict[str, str]:
    """Render every template of a chart. Returns {template_name: yaml}."""
    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        values = yaml.safe_load(f) or {}
    for vf in values_files or []:
        with open(vf) as f:
            values = _deep_merge(values, yaml.safe_load(f) or {})
    for sv in set_values or []:
        key, _, raw = sv.partition("=")
        _set_path(values, key, raw)
    ctx = {
        "Values": values,
        # Match real helm exactly: the release namespace comes from the
        # -n/--namespace flag (default "default"), never from values —
        # helm itself ignores a values.yaml `namespace:` key, so reading
        # it here would silently diverge from `helm template`.
        "Release": {
            "Name": release_name,
            "Namespace": namespace or "default",
        },
        "Chart": {
            "Name": chart_meta.get("name", ""),
            "Version": str(chart_meta.get("version", "")),
        },
    }
    tdir = os.path.join(chart_dir, "templates")
    out: dict[str, str] = {}
    for name in sorted(os.listdir(tdir)):
        if not name.endswith((".yaml", ".yml")):
            continue
        with open(os.path.join(tdir, name)) as f:
            body = render(f.read(), ctx)
        if body.strip():
            out[name] = body
    return out


def render_chart_docs(chart_dir: str, **kw: Any) -> list[dict]:
    """Render and YAML-parse a chart into its manifest documents."""
    docs: list[dict] = []
    for name, body in render_chart(chart_dir, **kw).items():
        try:
            for doc in yaml.safe_load_all(body):
                if doc:
                    docs.append(doc)
        except yaml.YAMLError as e:
            raise HelmliteError(f"{name}: invalid YAML after render: {e}") from e
    return docs

"""Small shared helpers (reference pkg/utils, internal/ktime, buildinfo)."""

from retina_tpu.utils.metric_names import *  # noqa: F401,F403
from retina_tpu.utils.ktime import boot_offset_ns, monotonic_to_wall_ns

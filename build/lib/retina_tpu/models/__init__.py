"""Detector/aggregator models composed from ops/ sketches.

The reference's analog layer is pkg/module/metrics: per-metric aggregators
implementing AdvMetricsInterface{Init, ProcessFlow, Clean} driven one flow
at a time (metrics_module.go:283-303). Here each model is a pure pytree
state + batched update, and the flagship TelemetryPipeline fuses all enabled
models into ONE jitted step per event batch.
"""

from retina_tpu.models.identity import IdentityMap  # noqa: F401
from retina_tpu.models.pipeline import (  # noqa: F401
    PipelineConfig,
    PipelineState,
    TelemetryPipeline,
)

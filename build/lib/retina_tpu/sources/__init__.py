"""Host-side event sources — the kernel-hook analog layer (L1).

TPUs have no kernel hooks, so the reference's eBPF programs
(pkg/plugin/*/_cprog/*.c) map to host-side sources that produce the same
fixed-width event records (SURVEY.md §7 design mapping):

- :mod:`retina_tpu.sources.pcapdecode` — packet-bytes → records decoder
  (the packetparser.c parse path), vectorized with numpy, with an optional
  C++ fast path (retina_tpu.native).
- :mod:`retina_tpu.events.synthetic` — trafficgen analog.
- :mod:`retina_tpu.sources.live` — AF_PACKET live capture (root-gated).
"""

from retina_tpu.sources.pcapdecode import (
    PcapDecodeResult,
    decode_pcap_bytes,
    decode_pcap_file,
    synthesize_pcap,
)

"""Cilium monitor-socket payload parsing -> event records.

Reference analog: pkg/plugin/ciliumeventobserver/parser_linux.go — the
gob-decoded ``payload.Payload`` (sources/gobcodec.py) carries a BPF perf
event in ``Data``; ``Data[0]`` discriminates the monitor message type and
the rest is a fixed C-struct header followed by the captured packet
(Ethernet frame). The reference hands these to Cilium's hubble parser;
here the headers are parsed directly and the embedded frames run through
the SAME vectorized packet decoder every other source uses
(sources/pcapdecode.py) — one decode path, batch-vectorized, instead of
a per-event object pipeline.

Struct layouts follow Cilium's stable datapath ABI (pkg/monitor/
datapath_drop.go / datapath_trace.go / datapath_policy.go): DropNotify
(36-byte header), TraceNotify V0/V1 (32/48 bytes, version at offset 14),
PolicyVerdictNotify (32 bytes), DebugCapture (24 bytes, its own layout —
datapath_debug.go). Offsets live in one table below so an ABI revision
is a one-line fix.
"""

from __future__ import annotations

import dataclasses
import struct
import time

import numpy as np

from retina_tpu.events.schema import (
    DIR_EGRESS,
    DIR_INGRESS,
    DIR_UNKNOWN,
    EV_DROP,
    EV_FORWARD,
    F,
    NUM_FIELDS,
    OP_FROM_NETWORK,
    OP_TO_ENDPOINT,
    OP_TO_NETWORK,
    OP_TO_STACK,
    VERDICT_DROPPED,
    VERDICT_FORWARDED,
)

# payload.Payload.Type (cilium pkg/monitor/payload/monitor_payload.go).
PAYLOAD_EVENT_SAMPLE = 9
PAYLOAD_RECORD_LOST = 2

# Monitor message types (cilium pkg/monitor/api/types.go iota order).
MSG_DROP = 1
MSG_DEBUG = 2
MSG_CAPTURE = 3
MSG_TRACE = 4
MSG_ACCESS_LOG = 5  # agent event (L7 log record) — not a perf event
MSG_AGENT = 6
MSG_POLICY_VERDICT = 7
MSG_RECORD_CAPTURE = 8
MSG_TRACE_SOCK = 9

# Cilium trace observation points (pkg/monitor/api/types.go TraceTo*/
# TraceFrom*) -> our OP_* / direction. Unlisted points keep
# OP_FROM_NETWORK + DIR_UNKNOWN.
_TRACE_OBS = {
    0: (OP_TO_ENDPOINT, DIR_INGRESS),  # to-lxc: delivery INTO the endpoint
    2: (OP_TO_STACK, DIR_EGRESS),  # to-host
    3: (OP_TO_STACK, DIR_EGRESS),  # to-stack
    4: (OP_TO_NETWORK, DIR_EGRESS),  # to-overlay
    11: (OP_TO_NETWORK, DIR_EGRESS),  # to-network
    5: (OP_TO_STACK, DIR_EGRESS),  # from-lxc: packet LEAVING the endpoint
    7: (OP_FROM_NETWORK, DIR_INGRESS),  # from-host
    8: (OP_FROM_NETWORK, DIR_INGRESS),  # from-stack
    9: (OP_FROM_NETWORK, DIR_INGRESS),  # from-overlay
    10: (OP_FROM_NETWORK, DIR_INGRESS),  # from-network
}

# Cilium drop-reason ids (pkg/monitor/api/drop.go, sparse 130+ space)
# folded into the repo's bounded reason axis (plugins/dropreason.py
# DROP_REASONS; pipeline rectangle is n_drop_reasons=16 wide). Unlisted
# Cilium reasons land in "cilium_other" instead of clamping.
REASON_POLICY_DENIED = 8
REASON_INVALID_PACKET = 9
REASON_INVALID_SRC_IP = 10
REASON_CT_INVALID = 11
REASON_UNSUPPORTED_PROTO = 12
REASON_CILIUM_OTHER = 13
_CILIUM_DROP_REASONS = {
    130: REASON_INVALID_PACKET,  # invalid source mac
    131: REASON_INVALID_PACKET,  # invalid destination mac
    132: REASON_INVALID_SRC_IP,
    133: REASON_POLICY_DENIED,
    134: REASON_INVALID_PACKET,
    135: REASON_CT_INVALID,  # CT: truncated or invalid header
    136: REASON_CT_INVALID,  # CT: missing tuple
    137: REASON_CT_INVALID,  # CT: unknown L4 protocol
    140: REASON_UNSUPPORTED_PROTO,  # unsupported L3 protocol
    142: REASON_UNSUPPORTED_PROTO,  # unknown L4 protocol
    181: REASON_POLICY_DENIED,  # policy denied (deny rule)
    # authentication / encryption / lb families -> other
}


def map_cilium_drop_reason(reason: int) -> int:
    """Sparse Cilium reason id -> bounded repo reason id.

    Ids inside the named repo enum (< 16, the pipeline's
    n_drop_reasons rectangle width) pass through untouched; everything
    else — the Cilium 130+ error band AND any id in 16..127 the
    rectangle would otherwise clamp to the unnamed bucket 15 — folds
    into a named bucket (cilium_other by default).
    """
    if reason < 16:
        return reason
    return _CILIUM_DROP_REASONS.get(reason, REASON_CILIUM_OTHER)


_DROP_HDR = 36  # DropNotify: ...DstID u32, Line u16, File u8,
#                 ExtError i8, Ifindex u32 (datapath_drop.go)
_TRACE_HDR_V0 = 32  # TraceNotify: version at offset 14
_TRACE_HDR_V1 = 48  # V1 appends OrigIP [16]byte
_POLICY_HDR = 32  # PolicyVerdictNotify (datapath_policy.go)
_DEBUG_CAP_HDR = 24  # DebugCapture: Type u8, SubType u8, Source u16,
#                      Hash u32, Len u32, OrigLen u32, Arg1 u32, Arg2 u32
#                      (datapath_debug.go) — NOT the TraceNotify layout


@dataclasses.dataclass
class ParsedEvent:
    """Per-event overlay applied onto the decoded packet record."""

    frame: bytes
    event_type: int = EV_FORWARD
    verdict: int = VERDICT_FORWARDED
    drop_reason: int = 0
    obs_point: int = OP_FROM_NETWORK
    direction: int = DIR_UNKNOWN
    ifindex: int = 0


def parse_perf_sample(data: bytes) -> ParsedEvent | None:
    """One perf-event ``Payload.Data`` -> (metadata, embedded frame).

    Returns None for message types that carry no packet (debug, agent,
    trace-sock, L7 access logs) — the reference's parser likewise
    forwards only Drop/Trace/PolicyVerdict/Capture to the flow decoder
    (parser_linux.go:78-86).
    """
    if not data:
        return None
    msg = data[0]
    if msg == MSG_DROP:
        if len(data) < _DROP_HDR:
            return None
        reason = map_cilium_drop_reason(data[1])  # SubType
        ifindex = struct.unpack_from("<I", data, 32)[0]
        return ParsedEvent(
            frame=data[_DROP_HDR:],
            event_type=EV_DROP,
            verdict=VERDICT_DROPPED,
            drop_reason=reason,
            obs_point=OP_TO_STACK,
            direction=DIR_UNKNOWN,
            ifindex=ifindex,
        )
    if msg == MSG_TRACE:
        if len(data) < _TRACE_HDR_V0:
            return None
        version = struct.unpack_from("<H", data, 14)[0]
        hdr = _TRACE_HDR_V1 if version >= 1 else _TRACE_HDR_V0
        if len(data) < hdr:
            return None
        obs, direction = _TRACE_OBS.get(
            data[1], (OP_FROM_NETWORK, DIR_UNKNOWN)
        )
        ifindex = struct.unpack_from("<I", data, 28)[0]
        return ParsedEvent(
            frame=data[hdr:],
            event_type=EV_FORWARD,
            verdict=VERDICT_FORWARDED,
            obs_point=obs,
            direction=direction,
            ifindex=ifindex,
        )
    if msg == MSG_CAPTURE:
        # DebugCapture: only emitted with datapath debug enabled; its
        # 24-byte header has no version field and no ifindex.
        if len(data) < _DEBUG_CAP_HDR:
            return None
        return ParsedEvent(
            frame=data[_DEBUG_CAP_HDR:],
            event_type=EV_FORWARD,
            verdict=VERDICT_FORWARDED,
            obs_point=OP_FROM_NETWORK,
            direction=DIR_UNKNOWN,
        )
    if msg == MSG_POLICY_VERDICT:
        if len(data) < _POLICY_HDR:
            return None
        verdict = struct.unpack_from("<i", data, 20)[0]
        if verdict < 0:
            return ParsedEvent(
                frame=data[_POLICY_HDR:],
                event_type=EV_DROP,
                verdict=VERDICT_DROPPED,
                drop_reason=map_cilium_drop_reason(-verdict & 0xFF),
            )
        return ParsedEvent(
            frame=data[_POLICY_HDR:],
            event_type=EV_FORWARD,
            verdict=VERDICT_FORWARDED,
        )
    # debug / agent / trace-sock / access-log, and MSG_RECORD_CAPTURE
    # (pcap-recorder captures use their own RecordCapture layout — not
    # yet supported, dropped rather than misparsed).
    return None


_PCAP_HDR = struct.pack(
    "<IHHiIII", 0xA1B23C4D, 2, 4, 0, 0, 65535, 1  # nanosecond pcap
)


def events_to_records(
    events: list[ParsedEvent], now_ns: int | None = None
) -> tuple[np.ndarray, dict[int, str]]:
    """Batch-decode the embedded frames and overlay per-event metadata.

    The frames are wrapped in an in-memory pcap whose per-packet
    timestamp is the EVENT INDEX, so after the vectorized decode (which
    may reject undecodable frames) each surviving record still knows
    which event it came from; real arrival timestamps are stamped last.
    """
    if not events:
        return np.zeros((0, NUM_FIELDS), np.uint32), {}
    from retina_tpu.sources.pcapdecode import decode_pcap_bytes

    parts = [_PCAP_HDR]
    for i, ev in enumerate(events):
        fr = ev.frame
        parts.append(struct.pack("<IIII", 0, i, len(fr), len(fr)))
        parts.append(fr)
    res = decode_pcap_bytes(b"".join(parts))
    rec = res.records
    if len(rec) == 0:
        return rec, res.dns_names
    # TS_LO carries the event index (see pcap wrap above).
    idx = rec[:, F.TS_LO].astype(np.int64)
    ev_type = np.array([e.event_type for e in events], np.uint32)[idx]
    verdict = np.array([e.verdict for e in events], np.uint32)[idx]
    reason = np.array([e.drop_reason for e in events], np.uint32)[idx]
    obs = np.array([e.obs_point for e in events], np.uint32)[idx]
    direction = np.array([e.direction for e in events], np.uint32)[idx]
    ifindex = np.array([e.ifindex for e in events], np.uint32)[idx]
    rec = rec.copy()
    rec[:, F.EVENT_TYPE] = ev_type
    rec[:, F.VERDICT] = verdict
    rec[:, F.DROP_REASON] = reason
    rec[:, F.IFINDEX] = ifindex
    # META: keep proto/flags from the packet decode, replace obs point +
    # direction with the monitor header's (layout: schema.pack_meta).
    meta = rec[:, F.META]
    meta = (
        (meta & np.uint32(0xFFFF0000))
        | (obs << np.uint32(8))
        | (direction << np.uint32(4))
        | (meta & np.uint32(0xF))
    )
    rec[:, F.META] = meta
    ts = np.uint64(now_ns if now_ns is not None else time.time_ns())
    rec[:, F.TS_LO] = np.uint32(ts & np.uint64(0xFFFFFFFF))
    rec[:, F.TS_HI] = np.uint32(ts >> np.uint64(32))
    return rec, res.dns_names

"""Minimal Go ``encoding/gob`` stream codec (decode + encode).

Cilium's monitor unix socket speaks gob: the agent writes consecutive
gob-encoded ``payload.Payload`` values (``Data []byte, CPU int,
Lost uint64, Type int``) and Retina's ciliumeventobserver decodes them
(reference: pkg/plugin/ciliumeventobserver/ciliumeventobserver_linux.go
:155-180 ``monitorLoop`` — ``gob.NewDecoder(conn)`` +
``pl.DecodeBinary``). This module implements the subset of the gob wire
format needed to interoperate with that stream — struct, slice, array,
map, and all basic types — as a pure-Python incremental decoder plus a
matching encoder (tests, replay tooling, and serving a monitor-socket
clone).

Wire format implemented (per the gob specification, pkg.go.dev/encoding/gob):

- unsigned int: one byte if < 128, else (256 - byte_count) then
  big-endian bytes;
- signed int: unsigned carrier, bit 0 = "complement" flag;
- float: float64 bits byte-reversed, sent as unsigned;
- string/[]byte: length then raw bytes;
- slice/map: count then elements / key-value pairs;
- struct: (field delta, value)* terminated by delta 0; zero fields are
  omitted;
- message: length-prefixed; body = signed type id, then either a type
  descriptor (id < 0, a ``wireType`` value describing type ``-id``) or
  a value of that type (non-struct top-level values are preceded by one
  zero delta byte).

Self-check: ``tests/test_gobcodec.py`` pins the worked ``Point{22,33}``
example from the gob documentation byte-for-byte.
"""

from __future__ import annotations

import struct as _struct
from typing import Any

# Bootstrap type ids (encoding/gob/type.go).
T_BOOL, T_INT, T_UINT, T_FLOAT = 1, 2, 3, 4
T_BYTES, T_STRING, T_COMPLEX, T_INTERFACE = 5, 6, 7, 8
T_WIRETYPE, T_ARRAYTYPE, T_COMMONTYPE, T_SLICETYPE = 16, 17, 18, 19
T_STRUCTTYPE, T_FIELDTYPE, T_FIELDSLICE, T_MAPTYPE = 20, 21, 22, 23
T_GOBENCODER, T_BINMARSHALER, T_TEXTMARSHALER = 24, 25, 26

FIRST_USER_ID = 65


class GobError(ValueError):
    pass


# ---------------------------------------------------------------------
# primitive readers/writers
# ---------------------------------------------------------------------
class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def byte(self) -> int:
        if self.pos >= len(self.buf):
            raise GobError("gob: truncated stream")
        b = self.buf[self.pos]
        self.pos += 1
        return b

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise GobError("gob: truncated stream")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out

    def uint(self) -> int:
        b = self.byte()
        if b < 0x80:
            return b
        n = 256 - b
        if n > 8:
            raise GobError(f"gob: uint byte count {n} > 8")
        v = 0
        for c in self.take(n):
            v = (v << 8) | c
        return v

    def int_(self) -> int:
        u = self.uint()
        if u & 1:
            return ~(u >> 1)
        return u >> 1

def _float_from_uint(u: int) -> float:
    # gob reverses the byte order of the IEEE-754 bits so small
    # exponents encode short; undo the reversal here.
    return _struct.unpack("<d", u.to_bytes(8, "big"))[0]


class _Writer:
    __slots__ = ("parts",)

    def __init__(self) -> None:
        self.parts: list[bytes] = []

    def bytes_(self, b: bytes) -> None:
        self.parts.append(b)

    def uint(self, v: int) -> None:
        if v < 0x80:
            self.parts.append(bytes([v]))
            return
        raw = v.to_bytes((v.bit_length() + 7) // 8, "big")
        self.parts.append(bytes([256 - len(raw)]) + raw)

    def int_(self, v: int) -> None:
        if v < 0:
            self.uint((~v << 1) | 1)
        else:
            self.uint(v << 1)

    def float_(self, v: float) -> None:
        (bits,) = _struct.unpack(">Q", _struct.pack("<d", v))
        self.uint(bits)

    def getvalue(self) -> bytes:
        return b"".join(self.parts)


# ---------------------------------------------------------------------
# type table
# ---------------------------------------------------------------------
class _WType:
    """A registered wire type: struct fields, or slice/array/map shape."""

    __slots__ = ("kind", "name", "fields", "elem", "key", "length")

    def __init__(self, kind: str, name: str = "", fields=None, elem=0,
                 key=0, length=0):
        self.kind = kind  # "struct" | "slice" | "array" | "map"
        self.name = name
        self.fields = fields or []  # [(name, type_id)]
        self.elem = elem
        self.key = key
        self.length = length


def _bootstrap_types() -> dict[int, _WType]:
    s = _WType
    return {
        T_COMMONTYPE: s("struct", "CommonType",
                        [("Name", T_STRING), ("Id", T_INT)]),
        T_ARRAYTYPE: s("struct", "ArrayType",
                       [("CommonType", T_COMMONTYPE), ("Elem", T_INT),
                        ("Len", T_INT)]),
        T_SLICETYPE: s("struct", "SliceType",
                       [("CommonType", T_COMMONTYPE), ("Elem", T_INT)]),
        T_STRUCTTYPE: s("struct", "StructType",
                        [("CommonType", T_COMMONTYPE),
                         ("Field", T_FIELDSLICE)]),
        T_FIELDTYPE: s("struct", "FieldType",
                       [("Name", T_STRING), ("Id", T_INT)]),
        T_FIELDSLICE: s("slice", "[]FieldType", elem=T_FIELDTYPE),
        T_MAPTYPE: s("struct", "MapType",
                     [("CommonType", T_COMMONTYPE), ("Key", T_INT),
                      ("Elem", T_INT)]),
        T_GOBENCODER: s("struct", "gobEncoderType",
                        [("CommonType", T_COMMONTYPE)]),
        T_BINMARSHALER: s("struct", "binaryMarshalerType",
                          [("CommonType", T_COMMONTYPE)]),
        T_TEXTMARSHALER: s("struct", "textMarshalerType",
                           [("CommonType", T_COMMONTYPE)]),
        T_WIRETYPE: s("struct", "wireType",
                      [("ArrayT", T_ARRAYTYPE), ("SliceT", T_SLICETYPE),
                       ("StructT", T_STRUCTTYPE), ("MapT", T_MAPTYPE),
                       ("GobEncoderT", T_GOBENCODER),
                       ("BinaryMarshalerT", T_BINMARSHALER),
                       ("TextMarshalerT", T_TEXTMARSHALER)]),
    }


# ---------------------------------------------------------------------
# decoder
# ---------------------------------------------------------------------
class GobStreamDecoder:
    """Incremental decoder: ``feed(data)`` returns the list of complete
    top-level values decoded so far (structs become dicts of the fields
    present on the wire — gob omits zero-valued fields, so consumers use
    ``.get(name, default)``)."""

    def __init__(self) -> None:
        self._buf = b""
        self._types = _bootstrap_types()

    # Go's gob caps messages at 1GB; anything larger in the length
    # prefix means a desynced/corrupt stream, not a big message.
    MAX_MESSAGE = 1 << 30

    def _try_length(self) -> int | None:
        """Parse the message length prefix: None = genuinely incomplete
        (wait for more bytes); GobError = corrupt (count byte out of
        range, or absurd length) — the stream cannot resynchronize."""
        if not self._buf:
            return None
        b = self._buf[0]
        if b < 0x80:
            return b
        n = 256 - b
        if n > 8:
            raise GobError(f"gob: length prefix byte count {n} > 8")
        if len(self._buf) < 1 + n:
            return None
        v = int.from_bytes(self._buf[1 : 1 + n], "big")
        if v > self.MAX_MESSAGE:
            raise GobError(f"gob: message length {v} exceeds 1GB cap")
        return v

    # -- message framing ----------------------------------------------
    def feed(self, data: bytes) -> list[Any]:
        """Returns complete top-level values decoded so far. Raises
        GobError on a CORRUPT stream (vs merely truncated) — gob framing
        is stateful, so the caller must drop the connection; treating
        corruption as 'incomplete' would stall forever while the buffer
        grows unboundedly."""
        self._buf += data
        out: list[Any] = []
        while True:
            msg_len = self._try_length()
            if msg_len is None:
                break  # incomplete length prefix
            r = _Reader(self._buf)
            r.uint()  # consume the validated prefix
            if len(self._buf) - r.pos < msg_len:
                break  # incomplete message body
            body = _Reader(self._buf[r.pos : r.pos + msg_len])
            self._buf = self._buf[r.pos + msg_len :]
            val = self._message(body)
            if val is not None:
                out.append(val[0])
        return out

    def _message(self, r: _Reader):
        type_id = r.int_()
        if type_id < 0:
            self._register(-type_id, self._decode_value(T_WIRETYPE, r))
            return None
        wt = self._types.get(type_id)
        if wt is None or wt.kind != "struct":
            delta = r.uint()  # singleton values carry one zero delta
            if delta != 0:
                raise GobError(f"gob: bad singleton delta {delta}")
        return (self._decode_value(type_id, r),)

    def _register(self, type_id: int, wire: Any) -> None:
        if not isinstance(wire, dict):
            raise GobError("gob: malformed type descriptor")
        if "StructT" in wire:
            st = wire["StructT"]
            common = st.get("CommonType", {})
            fields = [
                (f.get("Name", ""), f.get("Id", 0))
                for f in st.get("Field", [])
            ]
            self._types[type_id] = _WType(
                "struct", common.get("Name", ""), fields
            )
        elif "SliceT" in wire:
            st = wire["SliceT"]
            self._types[type_id] = _WType(
                "slice", elem=st.get("Elem", 0)
            )
        elif "ArrayT" in wire:
            st = wire["ArrayT"]
            self._types[type_id] = _WType(
                "array", elem=st.get("Elem", 0),
                length=st.get("Len", 0),
            )
        elif "MapT" in wire:
            st = wire["MapT"]
            self._types[type_id] = _WType(
                "map", key=st.get("Key", 0), elem=st.get("Elem", 0)
            )
        else:
            raise GobError(
                f"gob: unsupported type descriptor {sorted(wire)}"
            )

    # -- values --------------------------------------------------------
    def _decode_value(self, type_id: int, r: _Reader) -> Any:
        if type_id == T_BOOL:
            return r.uint() != 0
        if type_id == T_INT:
            return r.int_()
        if type_id == T_UINT:
            return r.uint()
        if type_id == T_FLOAT:
            return _float_from_uint(r.uint())
        if type_id == T_BYTES:
            return r.take(r.uint())
        if type_id == T_STRING:
            return r.take(r.uint()).decode("utf-8", "replace")
        if type_id == T_COMPLEX:
            return complex(
                _float_from_uint(r.uint()), _float_from_uint(r.uint())
            )
        wt = self._types.get(type_id)
        if wt is None:
            raise GobError(f"gob: unknown type id {type_id}")
        if wt.kind == "struct":
            out: dict[str, Any] = {}
            field = -1
            while True:
                delta = r.uint()
                if delta == 0:
                    return out
                field += delta
                if field >= len(wt.fields):
                    raise GobError(
                        f"gob: field {field} out of range for "
                        f"{wt.name or type_id}"
                    )
                name, ftype = wt.fields[field]
                out[name] = self._decode_value(ftype, r)
        if wt.kind in ("slice", "array"):
            n = r.uint()
            if wt.kind == "array" and n != wt.length:
                raise GobError("gob: array length mismatch")
            if n > len(r.buf):  # each element is >= 1 byte
                raise GobError("gob: slice count exceeds message size")
            return [self._decode_value(wt.elem, r) for _ in range(n)]
        if wt.kind == "map":
            n = r.uint()
            if n > len(r.buf) // 2:
                raise GobError("gob: map count exceeds message size")
            return {
                self._decode_value(wt.key, r): self._decode_value(
                    wt.elem, r
                )
                for _ in range(n)
            }
        raise GobError(f"gob: unhandled kind {wt.kind}")


# ---------------------------------------------------------------------
# encoder
# ---------------------------------------------------------------------
class GobStructEncoder:
    """Encoder for ONE struct type (the ``gob.NewEncoder`` analog for a
    homogeneous stream, which is exactly what the monitor socket is).

    ``fields`` is the Go-declaration-ordered list of (name, type_id)
    with type ids from the bootstrap basics (T_BYTES/T_INT/T_UINT/...).
    The first :meth:`encode` emits the type-descriptor message, like Go.
    """

    def __init__(self, name: str, fields: list[tuple[str, int]],
                 type_id: int = FIRST_USER_ID):
        self.name = name
        self.fields = fields
        self.type_id = type_id
        self._sent_types = False

    def _type_descriptor(self) -> bytes:
        w = _Writer()
        w.int_(-self.type_id)
        # wireType struct, field 2 = StructT
        w.uint(3)
        # StructType field 0: CommonType{Name, Id}
        w.uint(1)
        w.uint(1)
        nm = self.name.encode()
        w.uint(len(nm))
        w.bytes_(nm)
        w.uint(1)
        w.int_(self.type_id)
        w.uint(0)  # end CommonType
        # StructType field 1: Field []fieldType
        w.uint(1)
        w.uint(len(self.fields))
        for fname, ftid in self.fields:
            w.uint(1)
            fn = fname.encode()
            w.uint(len(fn))
            w.bytes_(fn)
            w.uint(1)
            w.int_(ftid)
            w.uint(0)
        w.uint(0)  # end StructType
        w.uint(0)  # end wireType
        return w.getvalue()

    @staticmethod
    def _frame(body: bytes) -> bytes:
        w = _Writer()
        w.uint(len(body))
        return w.getvalue() + body

    def encode(self, value: dict[str, Any]) -> bytes:
        """Encode one struct value (zero-valued fields omitted, per
        gob), prefixed by the type descriptor on the first call."""
        out = b""
        if not self._sent_types:
            out += self._frame(self._type_descriptor())
            self._sent_types = True
        w = _Writer()
        w.int_(self.type_id)
        prev = -1
        for i, (fname, ftid) in enumerate(self.fields):
            v = value.get(fname)
            if not v:  # gob omits zero values
                continue
            w.uint(i - prev)
            prev = i
            if ftid == T_BOOL:
                w.uint(1)
            elif ftid == T_INT:
                w.int_(int(v))
            elif ftid == T_UINT:
                w.uint(int(v))
            elif ftid == T_FLOAT:
                w.float_(float(v))
            elif ftid == T_BYTES:
                w.uint(len(v))
                w.bytes_(bytes(v))
            elif ftid == T_STRING:
                b = str(v).encode()
                w.uint(len(b))
                w.bytes_(b)
            else:
                raise GobError(f"encoder: unsupported field type {ftid}")
        w.uint(0)
        return out + self._frame(w.getvalue())

"""Pcap/packet decoding to event records — the packetparser.c analog.

Reference analog: pkg/plugin/packetparser/_cprog/packetparser.c —
``parse()`` (:118-227) extracts eth/IPv4/TCP/UDP headers plus the TCP
timestamp option (:42-115) into ``struct packet`` and emits it on a perf
ring. Here the same extraction runs on the host over pcap bytes, but
**vectorized**: one pass finds per-packet offsets (the only sequential
part of the format), then every header field for all packets is pulled
with numpy gathers — shaping the work the way the device wants it, instead
of per-packet branching.

DNS payloads (UDP :53) get a second, sparse pass building qname hashes +
a host-side string table (strings never cross to the device — schema.py).

Also provides :func:`synthesize_pcap` (build a real pcap from flow specs)
so tests and benches can round-trip: flows → pcap bytes → records.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib

import numpy as np

from retina_tpu.events.schema import (
    EV_DNS_REQ,
    EV_DNS_RESP,
    EV_FORWARD,
    F,
    NUM_FIELDS,
    OP_FROM_NETWORK,
    PROTO_TCP,
    PROTO_UDP,
    VERDICT_FORWARDED,
)

PCAP_MAGIC_US = 0xA1B2C3D4
PCAP_MAGIC_NS = 0xA1B23C4D


def dns_qname_hash(name: str | bytes) -> int:
    """Stable 32-bit hash for DNS query names (crc32 — host-side only).

    Hashes the raw label bytes with ASCII-only lowercasing so the value is
    bit-identical to the C++ decoder (decoder.cpp parse_dns), which never
    round-trips through unicode."""
    raw = name.encode("latin-1", "replace") if isinstance(name, str) else name
    lowered = bytes(c + 32 if 0x41 <= c <= 0x5A else c for c in raw)
    return zlib.crc32(lowered) & 0xFFFFFFFF


@dataclasses.dataclass
class PcapDecodeResult:
    records: np.ndarray  # (N, NUM_FIELDS) uint32
    dns_names: dict[int, str]  # qname hash -> name (host string table)
    n_packets_total: int  # all packets in the capture
    n_decoded: int  # IPv4 TCP/UDP packets decoded


def _find_offsets(data: bytes, ns: bool, swap: bool) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Sequential scan of pcap record headers → (ts_ns, pkt_off, caplen)."""
    fmt = "<IIII" if not swap else ">IIII"
    unpack = struct.Struct(fmt).unpack_from
    off = 24
    end = len(data)
    ts_list, off_list, len_list = [], [], []
    scale = 1 if ns else 1000
    while off + 16 <= end:
        ts_sec, ts_frac, incl, orig = unpack(data, off)
        if off + 16 + incl > end:
            break
        ts_list.append(ts_sec * 1_000_000_000 + ts_frac * scale)
        off_list.append(off + 16)
        len_list.append(incl)
        off += 16 + incl
    return (
        np.array(ts_list, np.uint64),
        np.array(off_list, np.int64),
        np.array(len_list, np.int64),
    )


def _gather_u8(buf: np.ndarray, offs: np.ndarray) -> np.ndarray:
    return buf[offs].astype(np.uint32)


def _gather_u16(buf: np.ndarray, offs: np.ndarray) -> np.ndarray:
    return (buf[offs].astype(np.uint32) << 8) | buf[offs + 1]


def _gather_u32(buf: np.ndarray, offs: np.ndarray) -> np.ndarray:
    return (
        (buf[offs].astype(np.uint32) << 24)
        | (buf[offs + 1].astype(np.uint32) << 16)
        | (buf[offs + 2].astype(np.uint32) << 8)
        | buf[offs + 3].astype(np.uint32)
    )


def decode_pcap_bytes(
    data: bytes,
    obs_point: int = OP_FROM_NETWORK,
    parse_dns: bool = True,
    prefer_native: bool = True,
) -> PcapDecodeResult:
    """Decode a pcap byte string into event records.

    Uses the C++ native decoder (retina_tpu.native, bit-identical) when
    built, falling back to the vectorized numpy path below. DNS name
    strings always come from a sparse host-Python pass (strings never
    enter the record tensor)."""
    if prefer_native:
        try:
            from retina_tpu.native import decode_pcap_native

            res = decode_pcap_native(data, obs_point)
        except ValueError:
            raise
        except Exception:
            res = None
        if res is not None:
            records, n_total = res
            names = _dns_name_pass(data) if parse_dns else {}
            return PcapDecodeResult(records, names, n_total, len(records))
    return _decode_pcap_numpy(data, obs_point, parse_dns)


def _dns_name_pass(data: bytes) -> dict[int, str]:
    """Sparse second pass: qname strings for UDP:53 packets only."""
    if len(data) < 24:
        return {}
    magic = struct.unpack_from("<I", data, 0)[0]
    if magic in (PCAP_MAGIC_US, PCAP_MAGIC_NS):
        swap, ns = False, magic == PCAP_MAGIC_NS
    else:
        magic_be = struct.unpack_from(">I", data, 0)[0]
        if magic_be not in (PCAP_MAGIC_US, PCAP_MAGIC_NS):
            return {}
        swap, ns = True, magic_be == PCAP_MAGIC_NS
    _, pkt_off, caplen = _find_offsets(data, ns, swap)
    names: dict[int, str] = {}
    for off, incl in zip(pkt_off, caplen):
        off, incl = int(off), int(incl)
        if incl < 14 + 20 + 8:
            continue
        if data[off + 12] != 0x08 or data[off + 13] != 0x00:
            continue
        ip_off = off + 14
        if (data[ip_off] >> 4) != 4 or data[ip_off + 9] != PROTO_UDP:
            continue
        ihl = (data[ip_off] & 0xF) * 4
        l4 = ip_off + ihl
        if incl < 14 + ihl + 8:
            continue
        sport = (data[l4] << 8) | data[l4 + 1]
        dport = (data[l4 + 2] << 8) | data[l4 + 3]
        if sport != 53 and dport != 53:
            continue
        parsed = _parse_dns(data, l4 + 8, off + incl)
        if parsed is not None:
            names[dns_qname_hash(parsed[0])] = parsed[0].decode(
                "ascii", "replace"
            )
    return names


def _decode_pcap_numpy(
    data: bytes,
    obs_point: int = OP_FROM_NETWORK,
    parse_dns: bool = True,
) -> PcapDecodeResult:
    """Pure numpy reference decoder (vectorized)."""
    if len(data) < 24:
        return PcapDecodeResult(
            np.zeros((0, NUM_FIELDS), np.uint32), {}, 0, 0
        )
    magic = struct.unpack_from("<I", data, 0)[0]
    if magic in (PCAP_MAGIC_US, PCAP_MAGIC_NS):
        swap = False
        ns = magic == PCAP_MAGIC_NS
    else:
        magic_be = struct.unpack_from(">I", data, 0)[0]
        if magic_be not in (PCAP_MAGIC_US, PCAP_MAGIC_NS):
            raise ValueError(f"not a pcap file (magic {magic:#x})")
        swap = True
        ns = magic_be == PCAP_MAGIC_NS

    ts_ns, pkt_off, caplen = _find_offsets(data, ns, swap)
    n_total = len(pkt_off)
    if n_total == 0:
        return PcapDecodeResult(
            np.zeros((0, NUM_FIELDS), np.uint32), {}, 0, 0
        )

    buf = np.frombuffer(data, np.uint8)

    # --- Ethernet: keep IPv4 with room for eth+ip headers ---
    # Every gather masks its offsets to 0 first: rows already rejected may
    # have offsets past the end of the capture buffer.
    ok = caplen >= 14 + 20
    safe = lambda offs: np.where(ok, offs, 0)
    ethertype = np.where(ok, _gather_u16(buf, safe(pkt_off + 12)), 0)
    ok &= ethertype == 0x0800

    # --- IPv4 header (packetparser.c parse() IPv4 block) ---
    ip_off = pkt_off + 14
    vihl = np.where(ok, _gather_u8(buf, safe(ip_off)), 0)
    ihl = (vihl & 0xF) * 4
    ok &= (vihl >> 4) == 4
    total_len = np.where(ok, _gather_u16(buf, safe(ip_off + 2)), 0)
    proto = np.where(ok, _gather_u8(buf, safe(ip_off + 9)), 0)
    ok &= (proto == PROTO_TCP) | (proto == PROTO_UDP)
    src_ip = np.where(ok, _gather_u32(buf, safe(ip_off + 12)), 0)
    dst_ip = np.where(ok, _gather_u32(buf, safe(ip_off + 16)), 0)

    l4_off = ip_off + ihl
    ok &= caplen >= (14 + ihl + np.where(proto == PROTO_TCP, 20, 8))

    safe_l4 = np.where(ok, l4_off, 0)
    sport = np.where(ok, _gather_u16(buf, safe_l4), 0)
    dport = np.where(ok, _gather_u16(buf, safe_l4 + 2), 0)

    is_tcp = ok & (proto == PROTO_TCP)
    tcp_at = np.where(is_tcp, safe_l4, 0)  # UDP rows may sit at buffer end
    tcp_flags = np.where(is_tcp, _gather_u8(buf, tcp_at + 13), 0)
    doff = np.where(is_tcp, (_gather_u8(buf, tcp_at + 12) >> 4) * 4, 8)

    # --- TCP timestamp option (packetparser.c:42-115): walk option
    # bytes for all TCP packets at once, at most 40 lock-step steps.
    tsval = np.zeros(n_total, np.uint32)
    tsecr = np.zeros(n_total, np.uint32)
    has_opts = is_tcp & (doff > 20) & (caplen >= 14 + ihl + doff)
    if has_opts.any():
        opt_start = safe_l4 + 20
        opt_len = np.where(has_opts, doff - 20, 0)
        pos = np.zeros(n_total, np.int64)
        active = has_opts.copy()
        for _ in range(40):
            if not active.any():
                break
            cur = opt_start + pos
            kind = np.where(active, _gather_u8(buf, np.where(active, cur, 0)), 0)
            # kind 0 = end, 1 = nop, 8 = timestamps (len 10)
            is_ts = active & (kind == 8) & (pos + 10 <= opt_len)
            ts_at = np.where(is_ts, cur, 0)
            tsval = np.where(is_ts, _gather_u32(buf, ts_at + 2), tsval)
            tsecr = np.where(is_ts, _gather_u32(buf, ts_at + 6), tsecr)
            active &= ~is_ts & (kind != 0)
            # A non-NOP option kind with no room left for its length byte
            # ends the walk (decoder.cpp: `if (p + 1 >= opt_len) break`) —
            # and keeps the length-byte gather below in bounds even when
            # the options region ends exactly at the capture buffer end.
            active &= (kind == 1) | (pos + 1 < opt_len)
            need_len = active & (kind != 1)
            length = np.where(
                kind == 1, 1, np.where(
                    need_len, np.maximum(
                        _gather_u8(buf, np.where(need_len, cur + 1, 0)), 2
                    ), 1
                )
            )
            pos = pos + np.where(kind == 1, 1, length)
            active &= pos < opt_len

    # --- assemble records ---
    idx = np.nonzero(ok)[0]
    n = len(idx)
    rec = np.zeros((n, NUM_FIELDS), np.uint32)
    ts_sel = ts_ns[idx]
    rec[:, F.TS_LO] = (ts_sel & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    rec[:, F.TS_HI] = (ts_sel >> np.uint64(32)).astype(np.uint32)
    rec[:, F.SRC_IP] = src_ip[idx]
    rec[:, F.DST_IP] = dst_ip[idx]
    rec[:, F.PORTS] = (sport[idx] << np.uint32(16)) | dport[idx]
    direction = 1 if obs_point in (OP_FROM_NETWORK, 1) else 2
    rec[:, F.META] = (
        (proto[idx] << np.uint32(24))
        | (tcp_flags[idx] << np.uint32(16))
        | (np.uint32(obs_point) << np.uint32(8))
        | np.uint32(direction << 4)
    )
    rec[:, F.BYTES] = total_len[idx]
    rec[:, F.PACKETS] = 1
    rec[:, F.VERDICT] = VERDICT_FORWARDED
    rec[:, F.TSVAL] = tsval[idx]
    rec[:, F.TSECR] = tsecr[idx]
    rec[:, F.EVENT_TYPE] = EV_FORWARD

    # --- DNS second pass (sparse; strings stay host-side) ---
    dns_names: dict[int, str] = {}
    if parse_dns:
        is_dns_sel = (proto[idx] == PROTO_UDP) & (
            (sport[idx] == 53) | (dport[idx] == 53)
        )
        for j in np.nonzero(is_dns_sel)[0]:
            i = idx[j]
            payload_off = int(l4_off[i]) + 8
            payload_end = int(pkt_off[i]) + int(caplen[i])
            parsed = _parse_dns(data, payload_off, payload_end)
            if parsed is None:
                continue
            qname, qtype, rcode, is_resp = parsed
            h = dns_qname_hash(qname)
            dns_names[h] = qname.decode("ascii", "replace")
            rec[j, F.DNS] = (
                ((qtype & 0xFFFF) << 16) | ((rcode & 0xFF) << 8)
                | (2 if is_resp else 1)
            )
            rec[j, F.DNS_QHASH] = h
            rec[j, F.EVENT_TYPE] = EV_DNS_RESP if is_resp else EV_DNS_REQ

    return PcapDecodeResult(rec, dns_names, n_total, n)


def _parse_dns(data: bytes, off: int, end: int):
    """Parse DNS header + first question. Returns (qname_raw: bytes, qtype,
    rcode, is_response) or None. The raw label bytes (not a unicode
    round-trip) are what gets hashed — decoder.cpp parse_dns parity,
    including its rejection of truncated labels and names > 255 bytes."""
    if end - off < 12:
        return None
    flags = struct.unpack_from(">H", data, off + 2)[0]
    qdcount = struct.unpack_from(">H", data, off + 4)[0]
    if qdcount < 1:
        return None
    is_resp = bool(flags & 0x8000)
    rcode = flags & 0xF
    labels: list[bytes] = []
    nlen = 0
    p = off + 12
    for _ in range(64):
        if p >= end:
            return None
        ln = data[p]
        if ln == 0:
            p += 1
            break
        if ln >= 0xC0:  # compression pointer — name done elsewhere
            p += 2
            break
        if p + 1 + ln > end or nlen + ln + 1 > 256:
            return None
        labels.append(data[p + 1 : p + 1 + ln])
        nlen += ln + (1 if nlen else 0)  # dot only between labels
        p += 1 + ln
    if p + 4 > end:
        return None
    qtype = struct.unpack_from(">H", data, p)[0]
    return b".".join(labels), qtype, rcode, is_resp


def dns_names_from_frames(blob: bytes) -> dict[int, str]:
    """qname strings from a [u16 caplen][eth frame] blob — the DNS
    sidecar the native TPACKET_V3 ring emits (afpacket.cpp): the C path
    fills record hash lanes, the host string table fills here."""
    names: dict[int, str] = {}
    off = 0
    total = len(blob)
    while off + 2 <= total:
        (cl,) = struct.unpack_from("<H", blob, off)
        off += 2
        end = off + cl
        if end > total:
            break
        frame = blob[off:off + cl]
        off = end
        if cl < 14 + 20 + 8 or frame[12] != 0x08 or frame[13] != 0x00:
            continue
        if (frame[14] >> 4) != 4 or frame[14 + 9] != PROTO_UDP:
            continue
        ihl = (frame[14] & 0xF) * 4
        pay = 14 + ihl + 8
        parsed = _parse_dns(frame, pay, cl)
        if parsed is not None:
            names[dns_qname_hash(parsed[0])] = parsed[0].decode(
                "ascii", "replace"
            )
    return names


def decode_pcap_file(path: str, **kw) -> PcapDecodeResult:
    with open(path, "rb") as fh:
        return decode_pcap_bytes(fh.read(), **kw)


# ---------------------------------------------------------------------------
# Pcap synthesis (tests / benches round-trip real packet bytes).


def _build_packet(
    src_ip: int,
    dst_ip: int,
    sport: int,
    dport: int,
    proto: int,
    payload: bytes = b"",
    tcp_flags: int = 0x10,
    tsval: int = 0,
    tsecr: int = 0,
) -> bytes:
    eth = b"\x02\x00\x00\x00\x00\x01\x02\x00\x00\x00\x00\x02\x08\x00"
    if proto == PROTO_TCP:
        opts = b""
        if tsval or tsecr:
            opts = b"\x01\x01" + struct.pack(">BBII", 8, 10, tsval, tsecr)
        doff = (20 + len(opts)) // 4
        l4 = struct.pack(
            ">HHIIBBHHH", sport, dport, 1000, 2000, doff << 4,
            tcp_flags, 65535, 0, 0,
        ) + opts + payload
    else:
        l4 = struct.pack(">HHHH", sport, dport, 8 + len(payload), 0) + payload
    total = 20 + len(l4)
    ip = struct.pack(
        ">BBHHHBBHII", 0x45, 0, total, 0, 0, 64, proto, 0, src_ip, dst_ip
    )
    return eth + ip + l4


def _build_dns_payload(qname: str, qtype: int = 1, response: bool = False,
                       rcode: int = 0) -> bytes:
    flags = (0x8000 | rcode) if response else 0x0100
    hdr = struct.pack(">HHHHHH", 0x1234, flags, 1, 0, 0, 0)
    q = b"".join(
        bytes([len(lbl)]) + lbl.encode() for lbl in qname.split(".")
    ) + b"\x00" + struct.pack(">HH", qtype, 1)
    return hdr + q


def synthesize_pcap(packets: list[dict], ns: bool = True) -> bytes:
    """Build pcap bytes from packet specs (keys: src_ip, dst_ip, sport,
    dport, proto, ts_ns, tcp_flags, tsval, tsecr, dns_qname, dns_response,
    dns_rcode, dns_qtype)."""
    magic = PCAP_MAGIC_NS if ns else PCAP_MAGIC_US
    out = [struct.pack("<IHHiIII", magic, 2, 4, 0, 0, 65535, 1)]
    for p in packets:
        payload = b""
        if p.get("dns_qname"):
            payload = _build_dns_payload(
                p["dns_qname"],
                p.get("dns_qtype", 1),
                p.get("dns_response", False),
                p.get("dns_rcode", 0),
            )
        raw = _build_packet(
            p["src_ip"], p["dst_ip"], p.get("sport", 40000),
            p.get("dport", 80), p.get("proto", PROTO_TCP), payload,
            p.get("tcp_flags", 0x10), p.get("tsval", 0), p.get("tsecr", 0),
        )
        ts = p.get("ts_ns", 0)
        frac = ts % 1_000_000_000 if ns else (ts % 1_000_000_000) // 1000
        out.append(
            struct.pack("<IIII", ts // 1_000_000_000, frac, len(raw), len(raw))
        )
        out.append(raw)
    return b"".join(out)

"""Per-component timing of the pipeline step on TPU (ablation profile)."""
import sys, time
import numpy as np

def log(m):
    print(f"[{time.strftime('%H:%M:%S')}] {m}", file=sys.stderr, flush=True)

import jax
import jax.numpy as jnp
from functools import partial

log(f"devices: {jax.devices()}")

from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline
from retina_tpu.events.schema import F

B = 1 << 17
cfg = PipelineConfig()
gen = TrafficGen(n_flows=1_000_000, n_pods=2048, seed=42)
rec = jax.device_put(gen.batch(B))
ident = IdentityMap.build_host({0x0A000000 + i: i for i in range(1, 2048)}, n_slots=1 << 16)
p = TelemetryPipeline(cfg)
state = p.init_state()

col = lambda i: rec[:, i]
src_ip = col(F.SRC_IP); dst_ip = col(F.DST_IP)
ports = col(F.PORTS); meta = col(F.META)
proto = meta >> 24
bytes_, packets = col(F.BYTES), col(F.PACKETS)
mask = jnp.ones((B,), bool)
w = packets


def timeit(name, fn, *args, n=10):
    try:
        f = jax.jit(fn)
        out = f(*args)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = f(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / n
        log(f"{name:32s} {dt*1e3:8.2f} ms  ({B/dt/1e6:8.1f} M ev/s)")
    except Exception as e:
        log(f"{name:32s} FAILED {type(e).__name__}: {e}")


five = [src_ip, dst_ip, ports, proto]

timeit("identity.lookup x2", lambda s, d: (ident.lookup(s), ident.lookup(d)), src_ip, dst_ip)
timeit("cms only (flow_hh.cms.update)", lambda c: c.update(five, w), state.flow_hh.cms)
timeit("flow_hh full (cms+slots)", lambda h: h.update(five, w), state.flow_hh)
timeit("svc_hh full", lambda h: h.update([src_ip, dst_ip], w), state.svc_hh)
timeit("hll_flows (G=1)", lambda h: h.update(five, jnp.zeros_like(src_ip), mask), state.hll_flows)
timeit("hll_src_per_pod (G=4096)", lambda h: h.update([src_ip], jnp.zeros_like(src_ip), mask), state.hll_src_per_pod)
timeit("entropy x1", lambda e: e.update([src_ip], jnp.zeros_like(src_ip), jnp.ones((B,), jnp.float32)), state.entropy)
timeit("conntrack.process", lambda c: c.process(src_ip, dst_ip, ports, proto, (meta >> 16) & jnp.uint32(0xFF), jnp.uint32(1), bytes_, mask)[0], state.conntrack)

def dense(pf):
    lp = jnp.minimum(ident.lookup(dst_ip), jnp.uint32(cfg.n_pods - 1))
    d = jnp.zeros((B,), jnp.uint32)
    pf = pf.at[lp, d, 0].add(packets, mode="drop")
    pf = pf.at[lp, d, 1].add(bytes_, mode="drop")
    return pf
timeit("dense pod_forward scatter x2", dense, state.pod_forward)

def tcpflags(ptf):
    lp = jnp.minimum(ident.lookup(dst_ip), jnp.uint32(cfg.n_pods - 1))
    tf = (meta >> 16) & jnp.uint32(0xFF)
    for bit in range(8):
        has = ((tf >> bit) & 1).astype(bool)
        ptf = ptf.at[lp, bit].add(jnp.where(has, packets, 0), mode="drop")
    return ptf
timeit("tcpflags 8 scatters", tcpflags, state.pod_tcpflags)

step = p.jitted_step()
s2, _ = step(state, rec, jnp.uint32(B), jnp.uint32(1), ident, jnp.uint32(0))
jax.block_until_ready(s2.totals)
t0 = time.perf_counter()
n = 10
for i in range(n):
    s2, _ = step(s2, rec, jnp.uint32(B), jnp.uint32(2), ident, jnp.uint32(0))
jax.block_until_ready(s2.totals)
dt = (time.perf_counter() - t0) / n
log(f"{'FULL STEP':32s} {dt*1e3:8.2f} ms  ({B/dt/1e6:8.1f} M ev/s)")

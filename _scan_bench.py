import time, sys
import numpy as np
import jax, jax.numpy as jnp

def log(m): print(m, file=sys.stderr, flush=True)

# Calibrate: 50 chained 4096^3 matmuls inside one scan dispatch
def mm_body(c, _):
    return (c @ c) * jnp.bfloat16(1e-4), 0
@jax.jit
def mm50(c):
    c, _ = jax.lax.scan(mm_body, c, None, length=50)
    return c
a = jnp.ones((4096, 4096), jnp.bfloat16) * jnp.bfloat16(0.01)
r = mm50(a); _ = np.asarray(r)[:1]
t0 = time.perf_counter(); r = mm50(a); _ = np.asarray(r)[:1]
dt = (time.perf_counter() - t0) / 50
log(f"matmul 4096 in-scan: {dt*1e3:.3f} ms -> {2*4096**3/dt/1e12:.1f} TFLOPs")

from retina_tpu.events.synthetic import TrafficGen
from retina_tpu.models.identity import IdentityMap
from retina_tpu.models.pipeline import PipelineConfig, TelemetryPipeline

B = 1 << 17
N = 16
cfg = PipelineConfig()
gen = TrafficGen(n_flows=1_000_000, n_pods=2048, seed=42)
batches = np.stack([gen.batch(B) for _ in range(N)])
dev_batches = jax.device_put(batches)
ident = IdentityMap.build_host({0x0A000000+i: i for i in range(1,2048)}, n_slots=1<<16)
p = TelemetryPipeline(cfg)
state = p.init_state()

def body(s, rec):
    s, _ = p.step(s, rec, jnp.uint32(B), jnp.uint32(1), ident, jnp.uint32(0))
    return s, 0
@jax.jit
def run_scan(s, bs):
    s, _ = jax.lax.scan(body, s, bs)
    return s
log("compiling scan step...")
t0 = time.perf_counter()
state = run_scan(state, dev_batches)
_ = np.asarray(state.totals)[:1]
log(f"compile+first: {time.perf_counter()-t0:.1f}s")
t0 = time.perf_counter()
state = run_scan(state, dev_batches)
_ = np.asarray(state.totals)[:1]
dt = (time.perf_counter() - t0) / N
log(f"full step in-scan: {dt*1e3:.2f} ms/step -> {B/dt/1e6:.2f} M ev/s")
